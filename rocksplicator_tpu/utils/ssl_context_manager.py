"""Refreshable TLS contexts + per-connection auth for the RPC plane.

Reference: common/ssl_context_manager.{h,cpp} — a periodically-refreshed
SSLContext picked up by the thrift client pool/server
(thrift_client_pool.h:254-290 configures SSL on channels). Here:

- ``SslContextManager`` owns ONE ``ssl.SSLContext`` and reloads the
  cert chain into it when the cert/key/CA files change on disk (checked
  at most every ``refresh_interval`` seconds). Reloading into the same
  context object means in-flight asyncio servers pick the new certs up
  for every subsequent handshake without rebinding.
- Mutual TLS IS the per-connection auth: with ``ca_path`` set, the
  server requires and verifies a client certificate signed by that CA
  (``verify_mode=CERT_REQUIRED``), and clients verify the server chain.
- **Role binding**: CA membership alone would let any cluster cert
  impersonate any peer (a stolen CLIENT cert presented as a server).
  Minted certs carry an ExtendedKeyUsage of serverAuth or clientAuth,
  and ``check_peer_role(ssl_object)`` verifies the peer's EKU matches
  the side it is playing — the RPC server and client both call it
  right after the handshake and drop mismatched peers.
- Refresh-thread ownership is REFCOUNTED: every ``ensure_auto_refresh``
  must be paired with a ``release_auto_refresh`` (servers and client
  pools share managers; the thread stops when the last user releases).
"""

from __future__ import annotations

import logging
import os
import ssl
import threading
import time
from typing import Optional, Tuple

log = logging.getLogger(__name__)

DEFAULT_REFRESH_INTERVAL = 300.0


class SslContextManager:
    """One refreshable context, server- or client-side."""

    def __init__(
        self,
        cert_path: str,
        key_path: str,
        ca_path: Optional[str] = None,
        server_side: bool = True,
        refresh_interval: float = DEFAULT_REFRESH_INTERVAL,
        check_hostname: bool = False,
    ):
        self._cert_path = cert_path
        self._key_path = key_path
        self._ca_path = ca_path
        self._server_side = server_side
        self._refresh_interval = refresh_interval
        self._lock = threading.Lock()
        self._last_check = 0.0
        self._mtimes: Tuple = ()
        if server_side:
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        else:
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
            # RPC peers are addressed by IP from shard maps; identity is
            # proven by the CA-signed cert, not the hostname
            ctx.check_hostname = check_hostname
            if ca_path is None:
                # encrypt-without-verify mode (PROTOCOL_TLS_CLIENT
                # defaults to CERT_REQUIRED, which would fail every
                # handshake with no CA loaded)
                ctx.verify_mode = ssl.CERT_NONE
        self._ctx = ctx
        self._refresh_thread: Optional[threading.Thread] = None
        self._refresh_cond = threading.Condition(self._lock)
        self._refresh_users = 0
        self._load(initial=True)

    # -- internals ---------------------------------------------------------

    def _file_mtimes(self) -> Tuple:
        out = []
        for p in (self._cert_path, self._key_path, self._ca_path):
            if p is None:
                out.append(None)
                continue
            try:
                out.append(os.path.getmtime(p))
            except OSError:
                out.append(-1)
        return tuple(out)

    def _load(self, initial: bool = False) -> None:
        mtimes = self._file_mtimes()
        if not initial and mtimes == self._mtimes:
            return
        ca_changed = (
            not initial and self._ca_path is not None
            and mtimes[2] != self._mtimes[2]
        )
        self._ctx.load_cert_chain(self._cert_path, self._key_path)
        if self._ca_path:
            self._ctx.load_verify_locations(self._ca_path)
            if self._server_side:
                # mutual TLS: the client must present a CA-signed cert
                self._ctx.verify_mode = ssl.CERT_REQUIRED
        self._mtimes = mtimes
        if ca_changed:
            # load_verify_locations ACCUMULATES trust anchors on a live
            # context; rotating a CA to DISTRUST the old one requires a
            # process restart (asyncio pins the context object).
            log.warning(
                "ssl CA file %s changed: new CA added, but previously "
                "trusted CAs remain trusted until process restart",
                self._ca_path,
            )
        if not initial:
            log.info("ssl context refreshed from %s", self._cert_path)

    # -- API ---------------------------------------------------------------

    def get(self) -> ssl.SSLContext:
        """The context, refreshed from disk if files changed and the
        refresh interval elapsed. Always the SAME object — safe to hand
        to a long-lived asyncio server once."""
        if self._refresh_thread is not None and self._refresh_thread.is_alive():
            # the background thread owns refresh: never do disk IO on the
            # caller (clients call get() inside the asyncio event loop —
            # a blocking cert reload there stalls every in-flight RPC).
            # A DEAD thread (timed-out close, crashed loop) must not
            # disable refresh silently — fall through to inline mode.
            return self._ctx
        now = time.monotonic()
        with self._lock:
            if now - self._last_check >= self._refresh_interval:
                self._last_check = now
                try:
                    self._load()
                except (OSError, ssl.SSLError):
                    log.exception("ssl context refresh failed; keeping old")
        return self._ctx

    def force_refresh(self) -> None:
        with self._lock:
            self._last_check = time.monotonic()
            self._load()

    def ensure_auto_refresh(self) -> None:
        """Register a user of the background refresh thread and start it
        if needed. Servers need it because they call get() once at bind
        time; clients need it so get() on the event loop NEVER does disk
        IO. Pair every call with release_auto_refresh() — managers are
        shared across servers and pools, so ownership is refcounted.
        All lifecycle transitions happen under one lock: a release
        racing a fresh claim can never strand the new claimant without
        a live thread (the loop re-checks the user count every wake)."""
        if self._refresh_interval <= 0:
            return
        with self._lock:
            self._refresh_users += 1
            if (self._refresh_thread is not None
                    and self._refresh_thread.is_alive()):
                return
            self._refresh_thread = threading.Thread(
                target=self._refresh_loop, name="ssl-refresh", daemon=True)
            self._refresh_thread.start()

    def _refresh_loop(self) -> None:
        me = threading.current_thread()
        with self._lock:
            while True:
                if self._refresh_users <= 0 or self._refresh_thread is not me:
                    if self._refresh_thread is me:
                        self._refresh_thread = None
                    return
                # Condition releases the lock while waiting; release /
                # close notify to end the wait early
                self._refresh_cond.wait(self._refresh_interval)
                if self._refresh_users <= 0 or self._refresh_thread is not me:
                    if self._refresh_thread is me:
                        self._refresh_thread = None
                    return
                self._last_check = time.monotonic()
                try:
                    self._load()
                except (OSError, ssl.SSLError):
                    log.exception("ssl auto-refresh failed; keeping old")

    def release_auto_refresh(self) -> None:
        """Drop one refresh-thread user; the thread exits at zero."""
        with self._lock:
            if self._refresh_users > 0:
                self._refresh_users -= 1
            if self._refresh_users > 0:
                return
            self._refresh_cond.notify_all()
            thread = self._refresh_thread
        if thread is not None and thread is not threading.current_thread():
            # prompt, bounded reap; a re-claim racing this join keeps the
            # thread alive (it re-checks users) and the join just times out
            thread.join(timeout=2.0)

    def close(self) -> None:
        """Stop the refresh thread unconditionally (final teardown)."""
        with self._lock:
            self._refresh_users = 0
            self._refresh_cond.notify_all()
            thread = self._refresh_thread
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout=2.0)
            if thread.is_alive():
                log.warning("ssl-refresh thread did not stop in time")


def _new_key():
    from cryptography.hazmat.primitives.asymmetric import rsa

    return rsa.generate_private_key(public_exponent=65537, key_size=2048)


def _write_key(key, path: str) -> None:
    from cryptography.hazmat.primitives import serialization

    with open(path, "wb") as f:
        f.write(key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption(),
        ))


def _write_cert(cert, path: str) -> None:
    from cryptography.hazmat.primitives import serialization

    with open(path, "wb") as f:
        f.write(cert.public_bytes(serialization.Encoding.PEM))


def _issue_cert(ca_key, issuer_name, cn: str,
                san_ip: Optional[str] = "127.0.0.1",
                role: Optional[str] = None):
    """One leaf cert under ``issuer_name``, signed by ``ca_key`` —
    the single minting recipe shared by make_test_ca and reissue_cert.
    ``role`` ∈ {"server", "client"} stamps the matching ExtendedKeyUsage
    so a stolen client cert cannot impersonate a server (check_peer_role
    enforces it after the handshake)."""
    import datetime

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes
    from cryptography.x509.oid import ExtendedKeyUsageOID, NameOID

    now = datetime.datetime.now(datetime.timezone.utc)
    key = _new_key()
    builder = (
        x509.CertificateBuilder()
        .subject_name(x509.Name(
            [x509.NameAttribute(NameOID.COMMON_NAME, cn)]))
        .issuer_name(issuer_name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=1))
    )
    if san_ip:
        import ipaddress

        builder = builder.add_extension(
            x509.SubjectAlternativeName(
                [x509.IPAddress(ipaddress.ip_address(san_ip))]),
            critical=False,
        )
    if role is not None:
        oid = (ExtendedKeyUsageOID.SERVER_AUTH if role == "server"
               else ExtendedKeyUsageOID.CLIENT_AUTH)
        builder = builder.add_extension(
            x509.ExtendedKeyUsage([oid]), critical=False)
    return key, builder.sign(ca_key, hashes.SHA256())


# dotted-string OIDs as returned by ssl.SSLObject.getpeercert()
_EKU_SERVER_AUTH = "1.3.6.1.5.5.7.3.1"
_EKU_CLIENT_AUTH = "1.3.6.1.5.5.7.3.2"


class PeerRoleError(Exception):
    """Peer presented a CA-valid cert minted for the WRONG role."""


def check_peer_role(ssl_object, expect_role: str) -> None:
    """Post-handshake role binding: the peer's cert must carry the EKU
    for the side it is playing (``expect_role`` ∈ {"server", "client"}).
    Certs WITHOUT any EKU pass (externally-provisioned certs predating
    role stamping); certs WITH an EKU must include the right one.

    OpenSSL's default X509 purpose check already rejects wrong-EKU peers
    during the handshake in common configurations; this is the explicit
    application-layer backstop so role binding doesn't silently depend
    on a library default. ``ssl.SSLObject.getpeercert()``'s dict form
    does NOT expose EKUs, so the DER cert is parsed with the
    ``cryptography`` package (the same one that mints the certs).

    No-op when there is no peer cert OR the connection did not verify
    the peer (encrypt-only mode: server with no client-cert
    requirement, or client with verification off — an UNVERIFIED cert's
    EKU proves nothing, and binary_form getpeercert returns it even
    when verification is off)."""
    if ssl_object is None:
        return
    if ssl_object.context.verify_mode == ssl.CERT_NONE:
        return
    der = ssl_object.getpeercert(binary_form=True)
    if not der:
        return
    from cryptography import x509
    from cryptography.x509.oid import ExtensionOID

    cert = x509.load_der_x509_certificate(der)
    try:
        eku = cert.extensions.get_extension_for_oid(
            ExtensionOID.EXTENDED_KEY_USAGE).value
    except x509.ExtensionNotFound:
        return
    want = (_EKU_SERVER_AUTH if expect_role == "server"
            else _EKU_CLIENT_AUTH)
    have = {oid.dotted_string for oid in eku}
    if want not in have:
        raise PeerRoleError(
            f"peer cert EKU {sorted(have)} does not permit role "
            f"{expect_role!r}"
        )


def make_test_ca(dir_path: str, common_name: str = "rstpu-test-ca"):
    """Generate a CA + signed server/client certs for tests (the
    reference's tests ship fixture certs; we mint them fresh with the
    ``cryptography`` package). Returns a dict of file paths."""
    import datetime

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes
    from cryptography.x509.oid import NameOID

    os.makedirs(dir_path, exist_ok=True)
    now = datetime.datetime.now(datetime.timezone.utc)
    ca_key = _new_key()
    ca_name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, common_name)])
    ca_cert = (
        x509.CertificateBuilder()
        .subject_name(ca_name).issuer_name(ca_name)
        .public_key(ca_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=1))
        .add_extension(x509.BasicConstraints(ca=True, path_length=0),
                       critical=True)
        .sign(ca_key, hashes.SHA256())
    )
    paths = {
        "ca_cert": os.path.join(dir_path, "ca.pem"),
        "ca_key": os.path.join(dir_path, "ca.key"),
    }
    _write_cert(ca_cert, paths["ca_cert"])
    _write_key(ca_key, paths["ca_key"])
    for role in ("server", "client"):
        key, cert = _issue_cert(ca_key, ca_name, f"rstpu-test-{role}",
                                role=role)
        paths[f"{role}_cert"] = os.path.join(dir_path, f"{role}.pem")
        paths[f"{role}_key"] = os.path.join(dir_path, f"{role}.key")
        _write_cert(cert, paths[f"{role}_cert"])
        _write_key(key, paths[f"{role}_key"])
    return paths


def reissue_cert(certs: dict, role: str, out_cert: str, out_key: str,
                 san_ip: str = "127.0.0.1") -> None:
    """Mint a NEW cert for ``role`` under an existing test CA (rotation
    scenarios: genuinely different bytes, same trust chain)."""
    from cryptography import x509
    from cryptography.hazmat.primitives import serialization

    with open(certs["ca_key"], "rb") as f:
        ca_key = serialization.load_pem_private_key(f.read(), password=None)
    with open(certs["ca_cert"], "rb") as f:
        ca_cert = x509.load_pem_x509_certificate(f.read())
    key, cert = _issue_cert(
        ca_key, ca_cert.subject, f"rstpu-test-{role}-rotated", san_ip,
        role=role if role in ("server", "client") else None)
    _write_cert(cert, out_cert)
    _write_key(key, out_key)
