"""Refreshable TLS contexts + per-connection auth for the RPC plane.

Reference: common/ssl_context_manager.{h,cpp} — a periodically-refreshed
SSLContext picked up by the thrift client pool/server
(thrift_client_pool.h:254-290 configures SSL on channels). Here:

- ``SslContextManager`` owns ONE ``ssl.SSLContext`` and reloads the
  cert chain into it when the cert/key/CA files change on disk (checked
  at most every ``refresh_interval`` seconds). Reloading into the same
  context object means in-flight asyncio servers pick the new certs up
  for every subsequent handshake without rebinding.
- Mutual TLS IS the per-connection auth: with ``ca_path`` set, the
  server requires and verifies a client certificate signed by that CA
  (``verify_mode=CERT_REQUIRED``), and clients verify the server chain.
"""

from __future__ import annotations

import logging
import os
import ssl
import threading
import time
from typing import Optional, Tuple

log = logging.getLogger(__name__)

DEFAULT_REFRESH_INTERVAL = 300.0


class SslContextManager:
    """One refreshable context, server- or client-side."""

    def __init__(
        self,
        cert_path: str,
        key_path: str,
        ca_path: Optional[str] = None,
        server_side: bool = True,
        refresh_interval: float = DEFAULT_REFRESH_INTERVAL,
        check_hostname: bool = False,
    ):
        self._cert_path = cert_path
        self._key_path = key_path
        self._ca_path = ca_path
        self._server_side = server_side
        self._refresh_interval = refresh_interval
        self._lock = threading.Lock()
        self._last_check = 0.0
        self._mtimes: Tuple = ()
        if server_side:
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        else:
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
            # RPC peers are addressed by IP from shard maps; identity is
            # proven by the CA-signed cert, not the hostname
            ctx.check_hostname = check_hostname
            if ca_path is None:
                # encrypt-without-verify mode (PROTOCOL_TLS_CLIENT
                # defaults to CERT_REQUIRED, which would fail every
                # handshake with no CA loaded)
                ctx.verify_mode = ssl.CERT_NONE
        self._ctx = ctx
        self._refresh_thread: Optional[threading.Thread] = None
        self._refresh_stop = threading.Event()
        self._load(initial=True)

    # -- internals ---------------------------------------------------------

    def _file_mtimes(self) -> Tuple:
        out = []
        for p in (self._cert_path, self._key_path, self._ca_path):
            if p is None:
                out.append(None)
                continue
            try:
                out.append(os.path.getmtime(p))
            except OSError:
                out.append(-1)
        return tuple(out)

    def _load(self, initial: bool = False) -> None:
        mtimes = self._file_mtimes()
        if not initial and mtimes == self._mtimes:
            return
        ca_changed = (
            not initial and self._ca_path is not None
            and mtimes[2] != self._mtimes[2]
        )
        self._ctx.load_cert_chain(self._cert_path, self._key_path)
        if self._ca_path:
            self._ctx.load_verify_locations(self._ca_path)
            if self._server_side:
                # mutual TLS: the client must present a CA-signed cert
                self._ctx.verify_mode = ssl.CERT_REQUIRED
        self._mtimes = mtimes
        if ca_changed:
            # load_verify_locations ACCUMULATES trust anchors on a live
            # context; rotating a CA to DISTRUST the old one requires a
            # process restart (asyncio pins the context object).
            log.warning(
                "ssl CA file %s changed: new CA added, but previously "
                "trusted CAs remain trusted until process restart",
                self._ca_path,
            )
        if not initial:
            log.info("ssl context refreshed from %s", self._cert_path)

    # -- API ---------------------------------------------------------------

    def get(self) -> ssl.SSLContext:
        """The context, refreshed from disk if files changed and the
        refresh interval elapsed. Always the SAME object — safe to hand
        to a long-lived asyncio server once."""
        now = time.monotonic()
        with self._lock:
            if now - self._last_check >= self._refresh_interval:
                self._last_check = now
                try:
                    self._load()
                except (OSError, ssl.SSLError):
                    log.exception("ssl context refresh failed; keeping old")
        return self._ctx

    def force_refresh(self) -> None:
        with self._lock:
            self._last_check = time.monotonic()
            self._load()

    def ensure_auto_refresh(self) -> None:
        """Start the background refresh thread (idempotent). Needed by
        LONG-LIVED SERVERS: clients drive refresh via get() on every
        connect, but a server calls get() once at bind time — without
        this, a rotated cert would never be picked up."""
        if self._refresh_interval <= 0 or self._refresh_thread is not None:
            return
        with self._lock:
            if self._refresh_thread is not None:
                return

            def loop() -> None:
                while not self._refresh_stop.wait(self._refresh_interval):
                    try:
                        with self._lock:
                            self._last_check = time.monotonic()
                            self._load()
                    except (OSError, ssl.SSLError):
                        log.exception("ssl auto-refresh failed; keeping old")

            self._refresh_thread = threading.Thread(
                target=loop, name="ssl-refresh", daemon=True)
            self._refresh_thread.start()

    def close(self) -> None:
        self._refresh_stop.set()
        if self._refresh_thread is not None:
            self._refresh_thread.join(timeout=2.0)
            self._refresh_thread = None


def make_test_ca(dir_path: str, common_name: str = "rstpu-test-ca"):
    """Generate a CA + signed server/client certs for tests (the
    reference's tests ship fixture certs; we mint them fresh with the
    ``cryptography`` package). Returns a dict of file paths."""
    import datetime

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    os.makedirs(dir_path, exist_ok=True)
    now = datetime.datetime.now(datetime.timezone.utc)

    def new_key():
        return rsa.generate_private_key(public_exponent=65537, key_size=2048)

    def write_key(key, path):
        with open(path, "wb") as f:
            f.write(key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.TraditionalOpenSSL,
                serialization.NoEncryption(),
            ))

    def write_cert(cert, path):
        with open(path, "wb") as f:
            f.write(cert.public_bytes(serialization.Encoding.PEM))

    ca_key = new_key()
    ca_name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, common_name)])
    ca_cert = (
        x509.CertificateBuilder()
        .subject_name(ca_name).issuer_name(ca_name)
        .public_key(ca_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=1))
        .add_extension(x509.BasicConstraints(ca=True, path_length=0),
                       critical=True)
        .sign(ca_key, hashes.SHA256())
    )

    def issue(cn: str, san_ip: Optional[str] = "127.0.0.1"):
        key = new_key()
        builder = (
            x509.CertificateBuilder()
            .subject_name(x509.Name(
                [x509.NameAttribute(NameOID.COMMON_NAME, cn)]))
            .issuer_name(ca_name)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=1))
        )
        if san_ip:
            import ipaddress

            builder = builder.add_extension(
                x509.SubjectAlternativeName(
                    [x509.IPAddress(ipaddress.ip_address(san_ip))]),
                critical=False,
            )
        return key, builder.sign(ca_key, hashes.SHA256())

    paths = {
        "ca_cert": os.path.join(dir_path, "ca.pem"),
        "ca_key": os.path.join(dir_path, "ca.key"),
    }
    write_cert(ca_cert, paths["ca_cert"])
    write_key(ca_key, paths["ca_key"])
    for role in ("server", "client"):
        key, cert = issue(f"rstpu-test-{role}")
        paths[f"{role}_cert"] = os.path.join(dir_path, f"{role}.pem")
        paths[f"{role}_key"] = os.path.join(dir_path, f"{role}.key")
        write_cert(cert, paths[f"{role}_cert"])
        write_key(key, paths[f"{role}_key"])
    return paths


def reissue_cert(certs: dict, role: str, out_cert: str, out_key: str,
                 san_ip: str = "127.0.0.1") -> None:
    """Mint a NEW cert for ``role`` under an existing test CA (rotation
    scenarios: genuinely different bytes, same trust chain)."""
    import datetime
    import ipaddress

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    with open(certs["ca_key"], "rb") as f:
        ca_key = serialization.load_pem_private_key(f.read(), password=None)
    with open(certs["ca_cert"], "rb") as f:
        ca_cert = x509.load_pem_x509_certificate(f.read())
    now = datetime.datetime.now(datetime.timezone.utc)
    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    cert = (
        x509.CertificateBuilder()
        .subject_name(x509.Name(
            [x509.NameAttribute(NameOID.COMMON_NAME,
                                f"rstpu-test-{role}-rotated")]))
        .issuer_name(ca_cert.subject)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=1))
        .add_extension(
            x509.SubjectAlternativeName(
                [x509.IPAddress(ipaddress.ip_address(san_ip))]),
            critical=False,
        )
        .sign(ca_key, hashes.SHA256())
    )
    with open(out_cert, "wb") as f:
        f.write(cert.public_bytes(serialization.Encoding.PEM))
    with open(out_key, "wb") as f:
        f.write(key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption(),
        ))
