"""Unified retry story: exponential backoff + full jitter + retry budget.

Reference: common/s3util.cpp leans on the AWS SDK's default retry
strategy (exp backoff, jittered, bounded attempts). Before this module
each backend hand-rolled its own: s3.py had an inline
``2**attempt * 0.1`` sleep, hdfs.py retried nothing, and the follower
pull loop drew a uniform delay that never grew. One policy object now
covers all of them, with two properties the chaos harness depends on:

- **determinism**: every jitter draw goes through a caller-supplied (or
  per-call seeded) ``random.Random`` — same seed, same schedule, which
  is what makes ``RSTPU_FAILPOINTS`` chaos runs reproducible from a
  printed ``--seed``;
- **a retry budget**: a token bucket shared by a client's retries so a
  hard-down dependency degrades to fail-fast instead of multiplying
  load (the classic retry-storm amplifier at 4000-host scale).

Retries are visible: each one increments ``retry.attempts op=<op>`` on
/stats, so a chaos run can show exactly which recovery path absorbed an
injected fault.
"""

from __future__ import annotations

import math
import random
import threading
import time
from dataclasses import dataclass
from typing import Callable, List, Optional

__all__ = ["RetryPolicy", "RetryBudget", "retry_call", "backoff_step",
           "seeded_rng", "retry_after_hint"]


def retry_after_hint(exc: BaseException) -> Optional[float]:
    """The server's retry-after hint, in SECONDS, from a typed
    ``RETRY_LATER`` application error (round-19 tail armor: the
    admission edge sheds with ``data={"retry_after_ms": ...}`` sized to
    the bucket refill / measured backlog). Duck-typed on ``.code`` /
    ``.data`` so this layer needs nothing from the rpc package. None
    for every other exception shape — callers fall back to their
    policy's own jittered delay."""
    if getattr(exc, "code", None) != "RETRY_LATER":
        return None
    data = getattr(exc, "data", None)
    if not isinstance(data, dict):
        return None
    try:
        hint_ms = float(data.get("retry_after_ms"))
    except (TypeError, ValueError):
        return None
    return max(0.0, hint_ms / 1e3)


def seeded_rng(env_var: str = "RSTPU_RETRY_SEED") -> random.Random:
    """The one place the seed-pinning contract lives: a private RNG
    seeded from ``env_var`` when set (reproducible chaos runs), random
    otherwise. Every retry loop that jitters should draw from one of
    these, not the global ``random``."""
    import os

    seed = os.environ.get(env_var)
    return random.Random(int(seed) if seed else None)


class RetryBudget:
    """Token bucket bounding retries (not first attempts) per client.
    ``try_spend`` never blocks: an empty bucket means the caller should
    surface the error now instead of piling on a struggling backend."""

    def __init__(self, capacity: float = 10.0, refill_per_sec: float = 1.0):
        self.capacity = float(capacity)
        self.refill_per_sec = float(refill_per_sec)
        self._tokens = float(capacity)
        self._last = time.monotonic()
        self._lock = threading.Lock()

    def try_spend(self, cost: float = 1.0) -> bool:
        with self._lock:
            now = time.monotonic()
            self._tokens = min(
                self.capacity,
                self._tokens + (now - self._last) * self.refill_per_sec)
            self._last = now
            if self._tokens < cost:
                return False
            self._tokens -= cost
            return True


@dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff with full jitter (delay ~ U[floor,
    cap_attempt], cap growing ``multiplier``-fold per attempt up to
    ``max_delay``). ``floor`` defaults to 0 (AWS-style full jitter);
    callers whose delay doubles as politeness toward a control plane
    (the follower pull loop) set ``floor`` to keep a hard minimum.

    ``max_attempts`` counts the first try: 4 means one call + up to
    three retries. Attempt indices passed to :meth:`delay` are 0-based
    retry indices (0 = delay before the first retry).
    """

    max_attempts: int = 4
    base_delay: float = 0.1
    max_delay: float = 5.0
    multiplier: float = 2.0
    jitter: bool = True
    floor: float = 0.0

    def cap(self, attempt: int) -> float:
        # saturating exponentiation: long-lived retry loops (a follower
        # through an hours-long outage) pass unbounded attempt counts,
        # and multiplier**attempt overflows float around attempt ~1024 —
        # past the saturation exponent the cap IS max_delay
        if self.base_delay <= 0.0:
            return 0.0  # parity with base*mult**n for any attempt
        if self.base_delay >= self.max_delay or self.multiplier <= 1.0:
            return min(self.max_delay, self.base_delay)
        sat = math.log(self.max_delay / self.base_delay, self.multiplier)
        if attempt >= sat:
            return self.max_delay
        return self.base_delay * (self.multiplier ** attempt)

    def delay(self, attempt: int,
              rng: Optional[random.Random] = None) -> float:
        cap = self.cap(attempt)
        if not self.jitter:
            return cap
        return (rng or random).uniform(min(self.floor, cap), cap)

    def schedule(self, seed: Optional[int] = None) -> List[float]:
        """The full jittered delay sequence for one seeded run —
        deterministic under a fixed seed (tested)."""
        rng = random.Random(seed)
        return [self.delay(a, rng) for a in range(self.max_attempts - 1)]


def backoff_step(
    policy: RetryPolicy,
    attempt: int,
    *,
    op: str,
    budget: Optional[RetryBudget] = None,
    rng: Optional[random.Random] = None,
    sleep: Callable[[float], None] = time.sleep,
    hint: Optional[float] = None,
) -> bool:
    """One retry-accounting step — the ONE place retries are counted
    (``retry.attempts op=<op>`` on /stats), budget-gated, and slept.
    Returns False when the attempt count or budget is exhausted (caller
    surfaces its error); True after sleeping the jittered delay. Shared
    by :func:`retry_call` and loops that interleave their own
    status-code handling (the S3 client).

    ``hint`` (seconds, from :func:`retry_after_hint`) is a server-side
    retry-after floor: the delay becomes ``max(jittered, hint * (1 +
    U[0,0.25]))`` — honoring the admission edge's backlog estimate
    while re-jittering so a shed cohort doesn't return in lockstep."""
    if attempt >= policy.max_attempts - 1:
        return False
    if budget is not None and not budget.try_spend():
        return False
    try:
        from .stats import Stats, tagged

        Stats.get().incr(tagged("retry.attempts", op=op or "?"))
    except Exception:
        pass
    delay = policy.delay(attempt, rng)
    if hint is not None and hint > 0.0:
        delay = max(delay, hint * (1.0 + 0.25 * (rng or random).random()))
    sleep(delay)
    return True


def retry_call(
    fn: Callable,
    *,
    policy: RetryPolicy,
    classify: Callable[[BaseException], bool],
    op: str = "",
    budget: Optional[RetryBudget] = None,
    rng: Optional[random.Random] = None,
    sleep: Callable[[float], None] = time.sleep,
):
    """Call ``fn()`` under ``policy``. ``classify(exc)`` says whether an
    exception is transient (retryable); anything else — or attempt/budget
    exhaustion — re-raises the last error unchanged."""
    attempt = 0
    while True:
        try:
            return fn()
        except BaseException as e:
            if not classify(e):
                raise
            if not backoff_step(policy, attempt, op=op, budget=budget,
                                rng=rng, sleep=sleep,
                                hint=retry_after_hint(e)):
                raise
            attempt += 1
