"""HTTP status server for observability.

Reference: common/stats/status_server.{h,cpp} — libmicrohttpd server on port
9999 exposing ``/stats.txt``, ``/gflags.txt``, ``/dump_heap``,
``/rocksdb_info.txt`` via a pluggable endpoint→handler map, plus an index at
``/``. Here: stdlib ThreadingHTTPServer; ``/dump_heap`` is a
tracemalloc-based heap profile (start on first hit, report+stop on the
next), ``/threads.txt`` is a Python stack dump, and ``/rocksdb_info.txt``
maps to ``/storage_info.txt``. The tracing subsystem adds ``/traces``
(recent sampled traces as JSON, for machines and cross-process stitching)
and ``/traces.txt`` (per-trace waterfall, for humans).
"""

from __future__ import annotations

import io
import sys
import threading
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

from .flags import FLAGS
from .stats import Stats

EndpointHandler = Callable[[], str]


class StatusServer:
    _instance: Optional["StatusServer"] = None
    _instance_lock = threading.Lock()

    def __init__(
        self,
        port: int = 9999,
        extra_endpoints: Optional[Dict[str, EndpointHandler]] = None,
        host: str = "127.0.0.1",
    ):
        # Loopback by default: the endpoints expose thread stacks, flags,
        # and live counter key names. Binding all interfaces (the
        # reference's behavior) is an explicit opt-in via host="0.0.0.0".
        self._host = host
        self._port = port
        self._endpoints: Dict[str, EndpointHandler] = {
            "/stats.txt": lambda: Stats.get().dump_text(),
            # machine-readable siblings of /stats.txt: the Prometheus
            # text exposition (counters/gauges + log-bucket histograms
            # as native histogram lines) and the raw mergeable state the
            # spectator scrape consumes
            # cached (0.5s TTL): a 100-shard node's gauge sweep runs
            # once per TTL regardless of how many scrapers poll
            "/metrics": lambda: Stats.get().dump_prometheus_cached(),
            "/stats.json": _dump_stats_json,
            "/flags.txt": FLAGS.dump_text,
            "/gflags.txt": FLAGS.dump_text,  # reference-compatible alias
            "/threads.txt": _dump_threads,
            "/dump_heap": _dump_heap,
            "/traces": _dump_traces_json,
            "/traces.txt": _dump_traces_waterfall,
        }
        if extra_endpoints:
            self._endpoints.update(extra_endpoints)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @classmethod
    def start_status_server(
        cls,
        port: int = 9999,
        extra_endpoints: Optional[Dict[str, EndpointHandler]] = None,
        host: str = "127.0.0.1",
    ) -> "StatusServer":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls(port, extra_endpoints, host=host)
                cls._instance.start()
            return cls._instance

    @classmethod
    def reset_for_test(cls) -> None:
        with cls._instance_lock:
            if cls._instance is not None:
                cls._instance.stop()
            cls._instance = None

    def register_endpoint(self, path: str, handler: EndpointHandler) -> None:
        self._endpoints[path] = handler

    def start(self) -> None:
        endpoints = self._endpoints

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802
                path = self.path.split("?", 1)[0]
                if path == "/":
                    body = "".join(
                        f'<a href="{p}">{p}</a><br/>\n' for p in sorted(endpoints)
                    )
                    ctype = "text/html"
                elif path in endpoints:
                    try:
                        body = endpoints[path]()
                    except Exception as e:
                        self.send_response(500)
                        self.end_headers()
                        self.wfile.write(repr(e).encode())
                        return
                    ctype = "text/plain"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                data = body.encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, *args) -> None:  # silence per-request logs
                pass

        self._httpd = ThreadingHTTPServer((self._host, self._port), Handler)
        self._port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="status-server", daemon=True
        )
        self._thread.start()

    @property
    def port(self) -> int:
        return self._port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


def _dump_stats_json() -> str:
    import json

    return json.dumps(Stats.get().export_state(), indent=1, default=str)


def _dump_traces_json() -> str:
    """Recent sampled traces as JSON (observability/collector.py). Each
    span carries its process label, so stitching a cross-process trace is
    'fetch /traces from every node, union spans, join on trace_id'."""
    from ..observability.collector import SpanCollector

    return SpanCollector.get().to_json_text()


def _dump_traces_waterfall() -> str:
    from ..observability.collector import SpanCollector

    return SpanCollector.get().waterfall_text()


def _dump_threads() -> str:
    out = io.StringIO()
    frames = sys._current_frames()
    for t in threading.enumerate():
        out.write(f"--- thread {t.name} (daemon={t.daemon}) ---\n")
        frame = frames.get(t.ident or -1)
        if frame:
            traceback.print_stack(frame, file=out)
        out.write("\n")
    return out.getvalue()


def _dump_heap() -> str:
    """Heap profile endpoint (reference: /dump_heap via gperftools,
    status_server.cpp:125-143). tracemalloc is the Python-native profiler.
    First request starts tracing; the next request reports the top
    allocation sites and STOPS tracing, so one stray probe cannot leave
    the per-allocation overhead enabled for the process lifetime."""
    import tracemalloc

    if not tracemalloc.is_tracing():
        tracemalloc.start(16)
        return (
            "tracemalloc started (16-frame traces). "
            "Request /dump_heap again for a snapshot (tracing then stops).\n"
        )
    snap = tracemalloc.take_snapshot()
    current, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    stats = snap.statistics("lineno")
    out = io.StringIO()
    out.write(f"traced current={current}B peak={peak}B (tracing stopped)\n")
    out.write(f"top {min(50, len(stats))} allocation sites by size:\n")
    for s in stats[:50]:
        out.write(f"{s.size:>12}B {s.count:>8}x {s.traceback}\n")
    return out.getvalue()
