"""Per-dataset dynamic configuration.

Reference: common/dbconfig.{h,cpp} + common/config.h — singleton holding a
JSON config keyed by dataset (segment); currently one knob in the reference:
``replication_mode`` (ack mode) per dataset; hot-reloaded via FileWatcher
with an atomic shared_ptr swap (dbconfig.h:30-70).
"""

from __future__ import annotations

import json
import logging
import threading
from typing import Any, Dict, Optional

from .file_watcher import FileWatcher

log = logging.getLogger(__name__)


class DBConfig:
    """Immutable parsed config snapshot."""

    def __init__(self, raw: Dict[str, Any]):
        self._raw = raw

    def replication_mode(self, segment: str, default: int = 0) -> int:
        entry = self._raw.get(segment)
        if isinstance(entry, dict):
            return int(entry.get("replication_mode", default))
        return default

    def get(self, segment: str, key: str, default: Any = None) -> Any:
        entry = self._raw.get(segment)
        if isinstance(entry, dict):
            return entry.get(key, default)
        return default

    @property
    def raw(self) -> Dict[str, Any]:
        return self._raw


class DBConfigManager:
    """Singleton; atomic snapshot swap on file change."""

    _instance: Optional["DBConfigManager"] = None
    _instance_lock = threading.Lock()

    def __init__(self) -> None:
        self._config = DBConfig({})
        self._path: Optional[str] = None

    @classmethod
    def get(cls) -> "DBConfigManager":
        if cls._instance is None:
            with cls._instance_lock:
                if cls._instance is None:
                    cls._instance = cls()
        return cls._instance

    @classmethod
    def reset_for_test(cls) -> None:
        with cls._instance_lock:
            if cls._instance is not None and cls._instance._path is not None:
                FileWatcher.instance().remove_file(
                    cls._instance._path, cls._instance._on_content
                )
            cls._instance = None

    def load_from_file(self, path: str, watch: bool = True) -> None:
        if self._path is not None:
            FileWatcher.instance().remove_file(self._path, self._on_content)
        self._path = path
        if watch:
            FileWatcher.instance().add_file(path, self._on_content)
        else:
            try:
                with open(path, "rb") as f:
                    self._on_content(f.read())
            except OSError:
                log.warning("db config file missing: %s", path)

    def load_from_dict(self, raw: Dict[str, Any]) -> None:
        self._config = DBConfig(dict(raw))

    def _on_content(self, content: bytes) -> None:
        try:
            raw = json.loads(content.decode("utf-8")) if content.strip() else {}
        except (ValueError, UnicodeDecodeError):
            log.error("invalid db config JSON, keeping previous config")
            return
        if not isinstance(raw, dict):
            log.error("db config must be a JSON object, keeping previous config")
            return
        self._config = DBConfig(raw)

    @property
    def config(self) -> DBConfig:
        return self._config

    def get_replication_mode(self, segment: str, default: int = 0) -> int:
        return self._config.replication_mode(segment, default)
