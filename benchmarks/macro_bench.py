#!/usr/bin/env python
"""Serving-scale macro-benchmark: YCSB-style mixed workload against a
3-replica cluster, driven through the FULL stack (RPC client → router →
replication → engine).

Every PERF.md number through round 12 is a micro/meso bench of one path
in isolation; this harness measures the serving SLO instead — p50/p99
latency per op class against a sweep of offered throughput:

- **zipfian key popularity** (YCSB ZipfianGenerator shape) over the
  preloaded keyspace;
- **tunable op mix** (``--mix get=0.75,put=0.15,multi_get=0.05,scan=0.05``);
- **open-loop (Poisson) arrival**: requests are issued on a seeded
  Poisson schedule regardless of completions, and latency is measured
  from the INTENDED arrival time — so at overload, queueing delay shows
  up in the percentiles instead of being hidden by a closed loop
  slowing its own offered rate (the YCSB "coordinated omission" fix);
- a ≥3-point offered-throughput sweep, each point reporting p50/p99 per
  op class;
- an interleaved read-policy A/B (leader_only vs follower_ok(max_lag)):
  closed-loop reader saturation, the read-scaling acceptance number.

Topology: 3 OS processes (1 leader + 2 followers, semi-sync mode 1)
spawned by this script via its own ``--serve`` child mode, plus this
driver process as the client fleet. Reads ride the round-13
bounded-staleness ``read`` RPC through ``RpcRouter.read`` read-preference
policies; writes ride the ``write`` RPC to the leader.

    python -m benchmarks.macro_bench --shards 4 --preload_keys 2000 \
        --rates 300,600,1200 --duration 5 --ab \
        --out benchmarks/results/macro_bench.json

Artifacts carry the shared ``host_calibration`` block
(benchmarks/ab_runner.py) so numbers are comparable across hosts.
"""

from __future__ import annotations

import argparse
import asyncio
import bisect
import contextlib
import json
import os
import random
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.ab_runner import (emit_gated_artifact,  # noqa: E402
                                  host_calibration, run_interleaved,
                                  sched_ab_failures)

SEGMENT = "mac"
OP_CLASSES = ("get", "put", "multi_get", "scan")
DEFAULT_MIX = "get=0.75,put=0.15,multi_get=0.05,scan=0.05"


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# deterministic workload generators (unit-tested: same seed ⇒ same stream)
# ---------------------------------------------------------------------------


class ZipfianGenerator:
    """Zipfian key popularity over ``[0, n)``: P(rank r) ∝ 1/(r+1)^theta
    (YCSB ZipfianGenerator shape, theta=0.99 default), drawn via a
    precomputed inverse CDF + bisect. ``spread`` scatters ranks over the
    id space deterministically so hot keys don't all land on shard 0."""

    def __init__(self, n: int, theta: float = 0.99, seed: int = 0,
                 spread: bool = True):
        if n <= 0:
            raise ValueError("n must be positive")
        self.n = n
        self.theta = theta
        self._rng = random.Random(seed)
        cum: List[float] = []
        total = 0.0
        for rank in range(n):
            total += 1.0 / ((rank + 1) ** theta)
            cum.append(total)
        self._cum = cum
        self._total = total
        # rank -> key id permutation (seeded by n, NOT by the draw seed:
        # two generators over the same keyspace agree on which ids are
        # hot, regardless of their draw streams)
        if spread:
            perm = list(range(n))
            random.Random(n * 2654435761 % (1 << 31)).shuffle(perm)
            self._perm: Optional[List[int]] = perm
        else:
            self._perm = None

    def next(self) -> int:
        r = self._rng.random() * self._total
        rank = bisect.bisect_left(self._cum, r)
        rank = min(rank, self.n - 1)
        return self._perm[rank] if self._perm is not None else rank


def poisson_arrivals(rate_per_sec: float, duration_sec: float,
                     seed: int = 0) -> List[float]:
    """Open-loop arrival offsets (seconds from phase start): exponential
    inter-arrivals at ``rate_per_sec``, deterministic under ``seed``."""
    if rate_per_sec <= 0:
        return []
    rng = random.Random(seed)
    t = 0.0
    out: List[float] = []
    while True:
        t += rng.expovariate(rate_per_sec)
        if t >= duration_sec:
            return out
        out.append(t)


def parse_mix(spec: str) -> Dict[str, float]:
    mix: Dict[str, float] = {}
    for part in spec.split(","):
        name, _, w = part.partition("=")
        name = name.strip()
        if name not in OP_CLASSES:
            raise ValueError(f"unknown op class {name!r} in mix")
        mix[name] = float(w)
    total = sum(mix.values())
    if total <= 0:
        raise ValueError("mix weights must sum > 0")
    return {k: v / total for k, v in mix.items()}


def op_stream(mix: Dict[str, float], n: int, seed: int) -> List[str]:
    """Deterministic op-class assignment for ``n`` arrivals."""
    rng = random.Random(seed)
    names = list(mix)
    weights = [mix[k] for k in names]
    return rng.choices(names, weights=weights, k=n)


def percentile(sorted_vals: List[float], pct: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(len(sorted_vals) * pct / 100.0))
    return sorted_vals[idx]


# ---------------------------------------------------------------------------
# keys & values (deterministic: spot-checkable under concurrent puts)
# ---------------------------------------------------------------------------


def key_of(gid: int) -> bytes:
    return b"k%08d" % gid


def shard_of(gid: int, shards: int) -> int:
    return gid % shards


def preload_value(gid: int, value_bytes: int) -> bytes:
    v = b"l%08d." % gid
    return (v * (value_bytes // len(v) + 1))[:value_bytes]


def put_value(gid: int, value_bytes: int) -> bytes:
    v = b"p%08d." % gid
    return (v * (value_bytes // len(v) + 1))[:value_bytes]


# ---------------------------------------------------------------------------
# --serve child: one replica process (leader preloads, followers catch up)
# ---------------------------------------------------------------------------


def serve(args) -> int:
    from rocksplicator_tpu.replication import (ReplicaRole,
                                               ReplicationFlags,
                                               Replicator,
                                               StorageDbWrapper)
    from rocksplicator_tpu.storage import DB, DBOptions, WriteBatch
    from rocksplicator_tpu.utils.segment_utils import segment_to_db_name

    flags = ReplicationFlags(
        server_long_poll_ms=1000,
        ack_timeout_ms=2000,
        write_window=args.write_window,
        # TTL above the long-poll period: an IDLE follower's estimate
        # refreshes on every long-poll expiry (~1s), so bounded reads in
        # a read-only phase serve without probing; the staleness window
        # a client buys is max_lag seqs + this TTL of time
        read_info_ttl_ms=args.read_info_ttl_ms,
        pull_error_delay_min_ms=50,
        pull_error_delay_max_ms=250,
    )
    # Per-shard assignment: the legacy 3-replica shape (one role, every
    # shard, one upstream) or — round 22, the fleet topology — an
    # explicit ``--topo`` JSON list of [shard, role, upstream_port]
    # giving THIS node's hosted subset (leaders and followers mixed, a
    # different upstream peer per shard).
    if args.topo:
        assign = [(int(s), ReplicaRole[r.upper()],
                   ("127.0.0.1", int(up)) if up else None)
                  for s, r, up in json.loads(args.topo)]
    else:
        role = (ReplicaRole.LEADER if args.serve == "leader"
                else ReplicaRole.FOLLOWER)
        upstream = (("127.0.0.1", args.upstream_port)
                    if args.upstream_port else None)
        assign = [(s, role, upstream) for s in range(args.shards)]
    replicator = Replicator(port=args.port, flags=flags,
                            executor_threads=args.executor_threads)
    handler = admin_server = None
    if args.db_profile == "churn":
        # compaction-pressure profile (the --sched_ab arms): small
        # memtables + low L0 triggers + small files so the write-heavy
        # mix accumulates REAL L0 debt; whether the adaptive scheduler
        # acts on it comes from the inherited RSTPU_COMPACTION_SCHED
        db_options = lambda _seg: DBOptions(  # noqa: E731
            wal_ttl_seconds=3600.0,
            background_compaction=True,
            memtable_bytes=24 * 1024,
            level0_compaction_trigger=4,
            level0_slowdown_writes_trigger=8,
            level0_stop_writes_trigger=16,
            target_file_bytes=48 * 1024,
            max_bytes_for_level_base=96 * 1024,
        )
    else:
        db_options = lambda _seg: DBOptions(  # noqa: E731
            wal_ttl_seconds=3600.0)
    if args.admin_port:
        # the live-move variant: this replica also speaks the Admin RPC
        # plane (backup/restore/pause/role-change) so a DirectShardMove
        # can relocate a shard mid-bench; restored dbs must come up in
        # the same semi-sync mode the bench registers explicitly
        from rocksplicator_tpu.admin.handler import AdminHandler
        from rocksplicator_tpu.rpc.server import RpcServer
        from rocksplicator_tpu.utils.dbconfig import DBConfigManager

        DBConfigManager.get().load_from_dict(
            {SEGMENT: {"replication_mode": 1}})
        handler = AdminHandler(args.db_dir, replicator,
                               options_generator=db_options)
        admin_server = RpcServer(port=args.admin_port,
                                 ioloop=replicator.ioloop)
        admin_server.add_handler(handler)
        admin_server.start()
    dbs = []
    for s, role, upstream in assign:
        name = segment_to_db_name(SEGMENT, s)
        db = DB(os.path.join(args.db_dir, name), db_options(SEGMENT))
        if role is ReplicaRole.LEADER and args.preload_keys:
            # preload BEFORE replication registration: engine writes go
            # straight to the WAL, followers replay them on first pull.
            # gids are dealt round-robin across the TOTAL shard count
            # (shard = gid % --shards), so each leader preloads exactly
            # its residue class
            batch = None
            for gid in range(s, args.shards * args.preload_keys,
                             args.shards):
                if batch is None:
                    batch = WriteBatch()
                batch.put(key_of(gid), preload_value(gid, args.value_bytes))
                if batch.count() >= 64:
                    db.write(batch)
                    batch = None
            if batch is not None:
                db.write(batch)
        dbs.append(db)
        if handler is not None:
            # register through the admin plane (ApplicationDB) so move
            # RPCs and the replication plane see the same instance
            from rocksplicator_tpu.admin.application_db import \
                ApplicationDB

            app_db = ApplicationDB(name, db, role, replicator=replicator,
                                   upstream_addr=upstream,
                                   replication_mode=1)
            handler.db_manager.add_db(name, app_db)
        else:
            replicator.add_db(name, StorageDbWrapper(db), role,
                              upstream_addr=upstream, replication_mode=1)
    print(f"READY role={args.serve} port={replicator.port} "
          f"shards={len(assign)}", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    try:
        while not stop.wait(0.5):
            pass
    except KeyboardInterrupt:
        pass
    if admin_server is not None:
        admin_server.stop()
    if handler is not None:
        handler.close()
    replicator.stop()
    for db in dbs:
        if handler is None:
            db.close()  # admin-managed dbs were closed by handler.close
    return 0


# ---------------------------------------------------------------------------
# driver: cluster orchestration
# ---------------------------------------------------------------------------


def reserve_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def build_router(ports: List[int], shards: int):
    """Router + pool over the 3-replica layout (leader = ports[0]).
    Shared by the driver and the A/B worker processes."""
    from rocksplicator_tpu.rpc.client_pool import RpcClientPool
    from rocksplicator_tpu.rpc.ioloop import IoLoop
    from rocksplicator_tpu.rpc.router import ClusterLayout, RpcRouter

    layout: Dict = {SEGMENT: {"num_shards": shards}}
    marks = {0: "M", 1: "S", 2: "S"}
    for i, port in enumerate(ports):
        layout[SEGMENT][f"127.0.0.1:{port}:az-n{i}:{port}"] = [
            f"{s:05d}:{marks[i]}" for s in range(shards)]
    pool = RpcClientPool()
    router = RpcRouter(local_az="az-n0", pool=pool)
    router.update_layout(ClusterLayout.parse(json.dumps(layout).encode()))
    return IoLoop.default(), pool, router


class Cluster:
    """1 leader + 2 followers as OS processes, plus the router/pool the
    driver issues RPCs through. With ``with_move_node`` the children
    also serve the Admin RPC plane and a 4th (initially empty) node is
    spawned — the destination a mid-bench DirectShardMove relocates a
    shard onto."""

    def __init__(self, root: str, shards: int, preload_keys: int,
                 value_bytes: int, write_window: int,
                 read_info_ttl_ms: int, transport: str,
                 executor_threads: int, with_move_node: bool = False,
                 db_profile: str = "default",
                 extra_env: Optional[Dict[str, str]] = None,
                 with_admin: bool = False):
        self.shards = shards
        self.with_move_node = with_move_node
        self._moved: Dict[int, int] = {}  # shard -> current leader idx
        self.procs: List[subprocess.Popen] = []
        n = 4 if with_move_node else 3
        self.ports = [reserve_port() for _ in range(n)]
        # with_admin: admin RPC plane on the 3 replicas WITHOUT the 4th
        # move-destination node (the --cdc mode drives
        # start_message_ingestion against the leader's admin port)
        self.admin_ports = ([reserve_port() for _ in range(n)]
                            if (with_move_node or with_admin) else [])
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   RSTPU_TRANSPORT=transport)
        env.update(extra_env or {})
        env.pop("PALLAS_AXON_POOL_IPS", None)

        def spawn(role: str, idx: int, upstream: int,
                  node_shards: int) -> subprocess.Popen:
            port = self.ports[idx]
            cmd = [
                sys.executable, "-m", "benchmarks.macro_bench",
                "--serve", role, "--port", str(port),
                "--shards", str(node_shards),
                "--db_dir", os.path.join(root, f"{role}{port}"),
                "--preload_keys", str(preload_keys),
                "--value_bytes", str(value_bytes),
                "--write_window", str(write_window),
                "--read_info_ttl_ms", str(read_info_ttl_ms),
                "--executor_threads", str(executor_threads),
                "--db_profile", db_profile,
            ]
            if self.admin_ports:
                cmd += ["--admin_port", str(self.admin_ports[idx])]
            if upstream:
                cmd += ["--upstream_port", str(upstream)]
            return subprocess.Popen(
                cmd, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                text=True, env=env,
                cwd=os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__))))

        self.procs.append(spawn("leader", 0, 0, shards))
        self._wait_ready(self.procs[0], "leader")
        for i in (1, 2):
            self.procs.append(spawn("follower", i, self.ports[0],
                                    shards))
        if with_move_node:
            # the move destination: admin plane up, zero shards hosted
            self.procs.append(spawn("follower", 3, self.ports[0], 0))
        for p in self.procs[1:]:
            self._wait_ready(p, "follower")

        # per-process transport policy must match the children's
        os.environ["RSTPU_TRANSPORT"] = transport
        self.ioloop, self.pool, self.router = build_router(
            self.ports[:3], shards)

    def apply_move_layout(self, shard: int, new_leader_idx: int) -> None:
        """Re-teach the driver's router after a completed shard move:
        ``shard``'s leader is now node ``new_leader_idx`` (what the
        shardmap-agent file refresh does for real clients). CUMULATIVE:
        every move applied so far stays applied — the hot-shift
        rebalancer arm relocates several shards in one run, and a
        rebuild that forgot an earlier move would route that shard back
        to its RETIRED old leader."""
        from rocksplicator_tpu.rpc.router import ClusterLayout

        self._moved[shard] = new_leader_idx
        layout: Dict = {SEGMENT: {"num_shards": self.shards}}
        marks = {0: "M", 1: "S", 2: "S", 3: None}
        for i, port in enumerate(self.ports):
            entries = []
            for s in range(self.shards):
                moved_to = self._moved.get(s)
                if moved_to is not None:
                    # moved shard: leader on its new node, the two
                    # surviving followers unchanged, old leader retired
                    if i == moved_to:
                        mark = "M"
                    elif i in (1, 2):
                        mark = "S"
                    else:
                        mark = None
                else:
                    mark = marks[i]
                if mark:
                    entries.append(f"{s:05d}:{mark}")
            if entries:
                layout[SEGMENT][
                    f"127.0.0.1:{port}:az-n{i}:{port}"] = entries
        self.router.update_layout(
            ClusterLayout.parse(json.dumps(layout).encode()))

    @staticmethod
    def _wait_ready(proc: subprocess.Popen, what: str,
                    timeout: float = 120.0) -> None:
        import select

        # select before readline: a child that hangs BEFORE printing
        # READY (stale engine lock, import deadlock) must trip the
        # deadline, not block the whole bench on a parked readline
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            ready, _, _ = select.select([proc.stdout], [], [], 1.0)
            if not ready:
                if proc.poll() is not None:
                    raise RuntimeError(f"{what} exited before READY "
                                       f"(rc={proc.poll()})")
                continue
            line = proc.stdout.readline()
            if not line:
                raise RuntimeError(f"{what} exited before READY "
                                   f"(rc={proc.poll()})")
            if line.startswith("READY"):
                log(f"  {line.strip()}")
                return
        raise RuntimeError(f"{what} not READY within {timeout}s")

    def wait_catchup(self, total_keys: int, timeout: float = 120.0) -> None:
        """Every follower must serve a max_lag=0 read of the last
        preloaded key of EVERY shard before the timed phases start (a
        single-shard probe would let still-replaying shards bounce
        bounded reads into the first sweep point and skew it) — also
        the first exercise of the bounded read path end to end."""
        from rocksplicator_tpu.rpc.errors import RpcError
        from rocksplicator_tpu.utils.segment_utils import segment_to_db_name

        # last preloaded gid per shard: gids are dealt round-robin
        # (shard = gid % shards), so walk back from the end
        last_gids = {}
        for gid in range(total_keys - 1, total_keys - 1 - self.shards, -1):
            if gid >= 0:
                last_gids[shard_of(gid, self.shards)] = gid

        async def probe(port: int, shard: int, gid: int):
            return await self.pool.call(
                "127.0.0.1", port, "read",
                {"db_name": segment_to_db_name(SEGMENT, shard),
                 "op": "get", "keys": [key_of(gid)], "max_lag": 0},
                timeout=5.0)

        deadline = time.monotonic() + timeout
        # replicas only — the move-phase spare node (ports[3]) hosts
        # nothing until a move lands on it
        for port in self.ports[1:3]:
            for shard, gid in sorted(last_gids.items()):
                while True:
                    try:
                        r = self.ioloop.run_sync(
                            probe(port, shard, gid), timeout=10)
                        if r["values"][0] is not None:
                            break
                    except RpcError:
                        pass
                    if time.monotonic() > deadline:
                        raise RuntimeError(
                            f"follower :{port} shard {shard} never "
                            f"caught up ({timeout}s)")
                    time.sleep(0.25)
        log("  followers caught up (max_lag=0 reads served on "
            f"{len(last_gids)} shards)")

    def stop(self) -> None:
        try:
            self.ioloop.run_sync(self.pool.close(), timeout=10)
        except Exception:
            pass
        for p in self.procs:
            p.terminate()
        for p in self.procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


# ---------------------------------------------------------------------------
# open-loop mixed-workload phase
# ---------------------------------------------------------------------------


class PhaseResult:
    def __init__(self) -> None:
        self.lat: Dict[str, List[float]] = {op: [] for op in OP_CLASSES}
        self.errors: Dict[str, int] = {op: 0 for op in OP_CLASSES}
        self.bounced = 0
        self.by_role: Dict[str, int] = {}
        self.value_mismatches = 0

    def summarize(self, offered: float, duration: float) -> Dict:
        ops = {}
        completed = 0
        for op in OP_CLASSES:
            vals = sorted(self.lat[op])
            completed += len(vals)
            if not vals and not self.errors[op]:
                continue
            ops[op] = {
                "count": len(vals),
                "errors": self.errors[op],
                "p50_ms": round(percentile(vals, 50), 3),
                "p90_ms": round(percentile(vals, 90), 3),
                "p99_ms": round(percentile(vals, 99), 3),
                "mean_ms": round(sum(vals) / len(vals), 3) if vals else None,
            }
        return {
            "offered_per_sec": offered,
            "duration_sec": duration,
            "achieved_per_sec": round(completed / duration, 1),
            "ops": ops,
            "reads_by_role": dict(self.by_role),
            "read_bounces": self.bounced,
            "value_mismatches": self.value_mismatches,
        }


async def _run_open_loop(cluster: Cluster, policy, rate: float,
                         duration: float, total_keys: int,
                         value_bytes: int, mix: Dict[str, float],
                         seed: int, max_inflight: int,
                         server_get_sink: Optional[List[float]] = None,
                         sample_log: Optional[List] = None,
                         gid_source=None,
                         acked_puts: Optional[set] = None
                         ) -> PhaseResult:
    from rocksplicator_tpu.rpc.errors import RpcError
    from rocksplicator_tpu.storage import WriteBatch

    res = PhaseResult()
    arrivals = poisson_arrivals(rate, duration, seed)
    opnames = op_stream(mix, len(arrivals), seed + 1)
    zipf = ZipfianGenerator(total_keys, seed=seed + 2)
    shards = cluster.shards
    router = cluster.router
    loop = asyncio.get_running_loop()
    base_bounces = _router_bounces(cluster)
    sem = asyncio.Semaphore(max_inflight)
    expect = {}  # gid -> allowed values, lazily built for spot checks

    def allowed(gid: int):
        vals = expect.get(gid)
        if vals is None:
            vals = expect[gid] = (preload_value(gid, value_bytes),
                                  put_value(gid, value_bytes))
        return vals

    async def one_op(intended: float, op: str, gid: int):
        async with sem:
            try:
                if op == "put":
                    batch = WriteBatch().put(
                        key_of(gid), put_value(gid, value_bytes))
                    await router.write(SEGMENT, shard_of(gid, shards),
                                       batch.encode(), timeout=15.0)
                    if acked_puts is not None:
                        # durably acked: the hot-shift gate reads every
                        # one of these back after the run — a key that
                        # lost its put across a policy-driven move is
                        # an acked-write loss
                        acked_puts.add(gid)
                else:
                    if op == "get":
                        args = {"keys": [key_of(gid)]}
                    elif op == "multi_get":
                        # step by `shards`: gids are dealt round-robin
                        # (shard = gid % shards), so only same-residue
                        # keys live on the routed shard — stepping by 1
                        # would benchmark 3/4 guaranteed misses
                        args = {"keys": [
                            key_of((gid + j * shards) % total_keys)
                            for j in range(4)]}
                    else:  # scan
                        args = {"start": key_of(gid), "count": 10}
                    r = await router.read(
                        SEGMENT, shard_of(gid, shards), op=op,
                        policy=policy, timeout=15.0, **args)
                    role = r.get("source_role") or "?"
                    res.by_role[role] = res.by_role.get(role, 0) + 1
                    if op == "get" and server_get_sink is not None \
                            and r.get("serve_ms") is not None:
                        # server-reported serve time: the exact samples
                        # the fleet histogram buckets — the p99
                        # agreement check's bench side
                        server_get_sink.append(float(r["serve_ms"]))
                    if op == "get":
                        got = r["values"][0]
                        got = bytes(got) if got is not None else None
                        if got not in allowed(gid):
                            res.value_mismatches += 1
            except RpcError:
                res.errors[op] += 1
                if sample_log is not None:
                    sample_log.append((loop.time(), op, None))
                return
            # OPEN-LOOP latency: completion minus INTENDED arrival, so
            # dispatcher/queue delay counts against the server, not the
            # next request's budget
            lat_ms = (loop.time() - intended) * 1000.0
            res.lat[op].append(lat_ms)
            if sample_log is not None:
                # (completion time, op, latency) — the move phase
                # windows samples into before/during/after the flip
                sample_log.append((loop.time(), op, lat_ms))

    next_gid = gid_source or zipf.next
    t0 = loop.time()
    tasks = []
    for off, op in zip(arrivals, opnames):
        delay = (t0 + off) - loop.time()
        if delay > 0:
            await asyncio.sleep(delay)
        tasks.append(asyncio.ensure_future(
            one_op(t0 + off, op, next_gid())))
    if tasks:
        await asyncio.wait(tasks)
    res.bounced = int(_router_bounces(cluster) - base_bounces)
    return res


def _router_bounces(cluster) -> float:
    from rocksplicator_tpu.rpc.router import _READ_BOUNCE_CODES
    from rocksplicator_tpu.utils.stats import Stats

    total = 0.0
    stats = Stats.get()
    for code in _READ_BOUNCE_CODES:  # derived: can't drift from router
        total += stats.get_counter(
            f"router.read_bounces code={code.lower()}")
    return total


def run_phase(cluster: Cluster, policy, rate: float, duration: float,
              total_keys: int, value_bytes: int, mix: Dict[str, float],
              seed: int, max_inflight: int,
              server_get_sink: Optional[List[float]] = None) -> Dict:
    res = cluster.ioloop.run_sync(
        _run_open_loop(cluster, policy, rate, duration, total_keys,
                       value_bytes, mix, seed, max_inflight,
                       server_get_sink=server_get_sink),
        timeout=duration + 120)
    return res.summarize(rate, duration)


def run_move_phase(cluster: Cluster, root: str, policy, rate: float,
                   duration: float, total_keys: int, value_bytes: int,
                   mix: Dict[str, float], seed: int,
                   max_inflight: int) -> Dict:
    """One long open-loop phase (3 windows of ``duration``) with a LIVE
    leader move of shard 0 onto the spare node launched at the 1/3
    mark: snapshot → bulk-ingest → WAL-tail catch-up → paused cutover →
    epoch-stamped promote (DirectShardMove). Samples are windowed into
    before/during/after the move so the artifact records what a live
    move costs the serving p99 — the acceptance number for this
    scenario. Reads keep serving throughout (bounded-staleness reads
    bounce off the moving replica to its peers); writes see a brief
    WRITE_PAUSED/repoint window, counted as errors, then resume on the
    new leader."""
    from rocksplicator_tpu.cluster.shard_move import (DirectMovePlan,
                                                      DirectNode,
                                                      DirectShardMove,
                                                      MoveFlags)
    from rocksplicator_tpu.utils.segment_utils import segment_to_db_name

    sample_log: List = []
    move_info: Dict = {}

    def node(i: int) -> DirectNode:
        return DirectNode("127.0.0.1", cluster.admin_ports[i],
                          cluster.ports[i])

    def mover():
        time.sleep(duration)
        move_info["t_start"] = time.monotonic()
        try:
            plan = DirectMovePlan(
                db_name=segment_to_db_name(SEGMENT, 0),
                source=node(0), target=node(3), leader=node(0),
                followers=[node(1), node(2)],
                store_uri=os.path.join(root, "move-bucket"))
            timings = DirectShardMove(plan, flags=MoveFlags(
                catchup_lag_threshold=32, catchup_timeout=60.0,
                cutover_pause_ms=3000.0, poll_interval=0.05)).run()
            move_info.update(ok=True, timings_ms=timings)
        except Exception as e:
            move_info.update(ok=False, error=repr(e))
        move_info["t_end"] = time.monotonic()
        if move_info.get("ok"):
            # what the shardmap-agent file refresh does for real
            # clients: shard 0's leader is the spare node now
            cluster.apply_move_layout(0, 3)

    th = threading.Thread(target=mover, name="bench-mover", daemon=True)
    th.start()
    res = cluster.ioloop.run_sync(
        _run_open_loop(cluster, policy, rate, duration * 3, total_keys,
                       value_bytes, mix, seed, max_inflight,
                       sample_log=sample_log),
        timeout=duration * 3 + 180)
    th.join(timeout=120)
    t_start = move_info.get("t_start")
    t_end = move_info.get("t_end")
    inf = float("inf")
    windows: Dict[str, Dict] = {}
    for name, lo, hi in (("before", -inf, t_start or inf),
                         ("during", t_start or inf, t_end or inf),
                         ("after", t_end or inf, inf)):
        gets = sorted(lat for ts, op, lat in sample_log
                      if op == "get" and lat is not None
                      and lo <= ts < hi)
        windows[name] = {
            "get_count": len(gets),
            "get_errors": sum(1 for ts, op, lat in sample_log
                              if op == "get" and lat is None
                              and lo <= ts < hi),
            "get_p50_ms": round(percentile(gets, 50), 3) if gets else None,
            "get_p99_ms": round(percentile(gets, 99), 3) if gets else None,
            "put_count": sum(1 for ts, op, lat in sample_log
                             if op == "put" and lat is not None
                             and lo <= ts < hi),
            "put_errors": sum(1 for ts, op, lat in sample_log
                              if op == "put" and lat is None
                              and lo <= ts < hi),
        }
    return {
        "move": {k: move_info.get(k)
                 for k in ("ok", "error", "timings_ms")},
        "move_duration_ms": (round((t_end - t_start) * 1000.0, 1)
                             if t_start and t_end else None),
        "windows": windows,
        "phase": res.summarize(rate, duration * 3),
    }


# ---------------------------------------------------------------------------
# read-policy A/B (closed-loop saturation: the read-scaling number)
# ---------------------------------------------------------------------------


async def _run_read_saturation(cluster: Cluster, policy, duration: float,
                               total_keys: int, readers: int,
                               seed: int) -> Dict[str, float]:
    from rocksplicator_tpu.rpc.errors import RpcError

    zipf = ZipfianGenerator(total_keys, seed=seed)
    shards = cluster.shards
    router = cluster.router
    loop = asyncio.get_running_loop()
    lats: List[float] = []
    errors = [0]
    by_role: Dict[str, int] = {}
    stop_at = loop.time() + duration

    async def reader():
        while loop.time() < stop_at:
            gid = zipf.next()
            t1 = loop.time()
            try:
                r = await router.read(SEGMENT, shard_of(gid, shards),
                                      op="get", keys=[key_of(gid)],
                                      policy=policy, timeout=15.0)
            except RpcError:
                errors[0] += 1
                continue
            lats.append((loop.time() - t1) * 1000.0)
            role = r.get("source_role") or "?"
            by_role[role] = by_role.get(role, 0) + 1

    await asyncio.gather(*[reader() for _ in range(readers)])
    lats.sort()
    return {
        "reads_per_sec": round(len(lats) / duration, 1),
        "p50_ms": round(percentile(lats, 50), 3),
        "p99_ms": round(percentile(lats, 99), 3),
        "errors": float(errors[0]),
        "follower_share": round(
            by_role.get("FOLLOWER", 0) / max(1, len(lats)), 3),
    }


def ab_worker(args) -> int:
    """One closed-loop reader-fleet process (A/B child mode): saturates
    the cluster with gets under one read policy and prints one JSON
    line. Run as a process fleet so the CLIENT side scales past one
    Python interpreter's GIL — otherwise the A/B measures the driver,
    not the replicas."""
    from rocksplicator_tpu.rpc.router import ReadPolicy

    ports = [int(x) for x in args.ports.split(",")]
    policy = (ReadPolicy.leader_only() if args.ab_worker == "leader_only"
              else ReadPolicy.follower_ok(args.max_lag))
    ioloop, pool, _router = build_router(ports, args.shards)
    total_keys = args.shards * args.preload_keys
    cluster_view = _WorkerView(_router, args.shards, ioloop, pool)
    out = ioloop.run_sync(
        _run_read_saturation(cluster_view, policy, args.ab_duration,
                             total_keys, args.ab_readers, args.seed),
        timeout=args.ab_duration + 60)
    ioloop.run_sync(pool.close(), timeout=10)
    print(json.dumps(out), flush=True)
    return 0


class _WorkerView:
    """The slice of Cluster the saturation loop needs."""

    def __init__(self, router, shards, ioloop, pool):
        self.router = router
        self.shards = shards
        self.ioloop = ioloop
        self.pool = pool


def run_read_ab(cluster: Cluster, max_lag: int, duration: float,
                shards: int, preload_keys: int, readers: int,
                procs: int, reps: int, seed: int,
                transport: str) -> Dict:
    """Interleaved leader_only vs follower_ok saturation, each variant a
    FLEET of ``procs`` closed-loop worker processes (sum of reads/s;
    p99 reported as the worst worker's — conservative)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", RSTPU_TRANSPORT=transport)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    ports_arg = ",".join(str(p) for p in cluster.ports)

    def fleet(kind: str):
        def run():
            cmds = []
            for w in range(procs):
                cmds.append(subprocess.Popen(
                    [sys.executable, "-m", "benchmarks.macro_bench",
                     "--ab_worker", kind, "--ports", ports_arg,
                     "--shards", str(shards),
                     "--preload_keys", str(preload_keys),
                     "--max_lag", str(max_lag),
                     "--ab_duration", str(duration),
                     "--ab_readers", str(readers),
                     "--seed", str(seed + w * 7919)],
                    stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                    text=True, env=env,
                    cwd=os.path.dirname(os.path.dirname(
                        os.path.abspath(__file__)))))
            outs = []
            for p in cmds:
                stdout, _ = p.communicate(timeout=duration + 120)
                if p.returncode != 0:
                    raise RuntimeError(
                        f"ab worker rc={p.returncode}")
                outs.append(json.loads(stdout.strip().splitlines()[-1]))
            n = sum(o["reads_per_sec"] * duration for o in outs)
            return {
                "reads_per_sec": round(
                    sum(o["reads_per_sec"] for o in outs), 1),
                "p50_ms": round(sorted(
                    o["p50_ms"] for o in outs)[len(outs) // 2], 3),
                "p99_ms": round(max(o["p99_ms"] for o in outs), 3),
                "errors": sum(o["errors"] for o in outs),
                "follower_share": round(
                    sum(o["follower_share"] * o["reads_per_sec"]
                        for o in outs)
                    / max(1e-9, sum(o["reads_per_sec"] for o in outs)), 3),
                "worker_procs": procs,
                "total_reads": int(n),
            }
        return run

    return run_interleaved(
        [("leader_only", fleet("leader_only")),
         ("follower_ok", fleet("follower_ok"))],
        reps=reps, key="reads_per_sec")


# ---------------------------------------------------------------------------
# compaction-scheduler A/B (round 16: whole-cluster, serving-SLO number)
# ---------------------------------------------------------------------------


def run_sched_ab(args) -> Dict:
    """Interleaved A/B of the workload-adaptive compaction scheduler
    UNDER the macro-bench: each rep boots a FRESH 3-process cluster per
    arm — children inherit ``RSTPU_COMPACTION_SCHED`` (1 vs 0) and run
    the ``churn`` engine profile (small memtables, low L0 triggers) so
    the write-heavy mix accumulates real L0 debt — then runs one
    open-loop mixed phase at the SAME offered throughput and scrapes
    the leader's ``stats`` RPC for the scheduler counters and
    write-stall totals. Lower get p99 is better."""
    import shutil
    import tempfile

    from rocksplicator_tpu.rpc.router import ReadPolicy

    mix = parse_mix(args.sched_mix)
    total_keys = args.shards * args.preload_keys
    policy = ReadPolicy.follower_ok(args.max_lag)
    rep_no = [0]

    def arm(sched: str):
        name = "sched_on" if sched == "1" else "sched_off"

        def run() -> Dict:
            rep_no[0] += 1
            root = tempfile.mkdtemp(prefix="rstpu-macro-sched-")
            cluster = None
            try:
                log(f"sched_ab[{name}]: booting churn cluster "
                    f"(RSTPU_COMPACTION_SCHED={sched})")
                cluster = Cluster(
                    root, args.shards, args.preload_keys,
                    args.value_bytes, args.write_window,
                    args.read_info_ttl_ms, args.transport,
                    args.executor_threads, db_profile="churn",
                    extra_env={"RSTPU_COMPACTION_SCHED": sched})
                cluster.wait_catchup(total_keys)
                phase = run_phase(
                    cluster, policy, args.sched_rate,
                    args.sched_duration, total_keys, args.value_bytes,
                    mix, args.seed + 77 * rep_no[0], args.max_inflight)

                async def scrape(port: int):
                    return await cluster.pool.call(
                        "127.0.0.1", port, "stats", {}, timeout=10.0)

                # fleet totals: every replica compacts (followers apply
                # the same write stream), so stalls/picks sum across
                # all three processes
                counters: Dict[str, float] = {}
                stall_sum, stall_count = 0.0, 0
                for port in cluster.ports[:3]:
                    st = cluster.ioloop.run_sync(scrape(port), timeout=15)
                    for k, v in (st.get("counters") or {}).items():
                        counters[k] = counters.get(k, 0.0) + v["total"]
                    rec = (st.get("metrics") or {}).get(
                        "storage.write_stall_ms") or {}
                    stall_sum += float(rec.get("sum", 0.0))
                    stall_count += int(rec.get("count", 0))

                def csum(prefix: str) -> int:
                    return int(sum(v for k, v in counters.items()
                                   if k.startswith(prefix)))

                g = phase["ops"].get("get") or {}
                pw = phase["ops"].get("put") or {}
                return {
                    "get_p99_ms": g.get("p99_ms"),
                    "get_p50_ms": g.get("p50_ms"),
                    "put_p99_ms": pw.get("p99_ms"),
                    "achieved_per_sec": phase["achieved_per_sec"],
                    "get_errors": g.get("errors", 0),
                    "put_errors": pw.get("errors", 0),
                    "value_mismatches": phase["value_mismatches"],
                    "fleet_write_stall_ms": round(stall_sum, 1),
                    "fleet_write_stalls": stall_count,
                    "compaction.sched_picks": csum(
                        "compaction.sched_picks"),
                    "compaction.yields": csum("compaction.yields"),
                    "compaction.subcompactions": csum(
                        "compaction.subcompactions"),
                }
            finally:
                if cluster is not None:
                    cluster.stop()
                shutil.rmtree(root, ignore_errors=True)
        return run

    return run_interleaved(
        [("sched_off", arm("0")), ("sched_on", arm("1"))],
        reps=args.sched_reps, key="get_p99_ms", higher_is_better=False,
        log=log)


# ---------------------------------------------------------------------------
# overload A/B (round 19: tail armor — deadlines, admission, hedging)
# ---------------------------------------------------------------------------


@contextlib.contextmanager
def _bench_env(**overrides: str):
    """Set/restore env in the BENCH process: the client half of the
    tail armor (deadline stamping, hedging) reads env here, not in the
    children — an A/B that only flips the children's env would measure
    half the killswitch."""
    saved = {k: os.environ.get(k) for k in overrides}
    os.environ.update(overrides)
    try:
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _scrape_counter_sums(cluster: "Cluster",
                         prefixes: Tuple[str, ...]) -> Dict[str, float]:
    """Fleet totals of every stats counter under the given prefixes."""

    async def scrape(port: int):
        return await cluster.pool.call("127.0.0.1", port, "stats", {},
                                       timeout=10.0)

    sums: Dict[str, float] = {}
    for port in cluster.ports[:3]:
        st = cluster.ioloop.run_sync(scrape(port), timeout=15)
        for k, v in (st.get("counters") or {}).items():
            if k.startswith(prefixes):
                sums[k] = sums.get(k, 0.0) + v["total"]
    return sums


async def _run_tenant_loop(cluster: "Cluster",
                           tenant_rates: Dict[str, float],
                           duration: float, total_keys: int,
                           seed: int, max_inflight: int,
                           deadline_ms: float) -> Dict:
    """Open-loop per-tenant get storm at the LEADER (one admission
    point, so "10x quota" means what it says): every op runs under
    ``request_scope`` so the client stamps the tenant tag and a
    relative deadline budget — exactly what an armored application
    client does. Typed sheds (RETRY_LATER / DEADLINE_EXCEEDED) are
    counted per tenant, NOT as errors: shedding is the armor working.
    Latency is open-loop (completion minus intended arrival), so the
    OFF arm's queue explosion lands in the percentiles."""
    from rocksplicator_tpu.rpc.deadline import (DEADLINE_EXCEEDED,
                                                RETRY_LATER, Deadline,
                                                request_scope)
    from rocksplicator_tpu.rpc.errors import RpcApplicationError, RpcError
    from rocksplicator_tpu.rpc.router import ReadPolicy

    policy = ReadPolicy.leader_only()
    arrivals: List[Tuple[float, str]] = []
    for i, (tenant, rate) in enumerate(sorted(tenant_rates.items())):
        for off in poisson_arrivals(rate, duration, seed + 31 * i):
            arrivals.append((off, tenant))
    arrivals.sort()
    zipf = ZipfianGenerator(total_keys, seed=seed + 7)
    shards = cluster.shards
    router = cluster.router
    loop = asyncio.get_running_loop()
    sem = asyncio.Semaphore(max_inflight)
    per: Dict[str, Dict] = {
        t: {"lat": [], "shed": 0, "deadline_shed": 0, "errors": 0}
        for t in tenant_rates}

    async def one_op(intended: float, tenant: str, gid: int):
        rec = per[tenant]
        async with sem:
            try:
                with request_scope(
                        deadline=Deadline.after_ms(deadline_ms),
                        tenant=tenant):
                    await router.read(SEGMENT, shard_of(gid, shards),
                                      op="get", keys=[key_of(gid)],
                                      policy=policy, timeout=15.0)
            except RpcApplicationError as e:
                if e.code == RETRY_LATER:
                    rec["shed"] += 1
                elif e.code == DEADLINE_EXCEEDED:
                    rec["deadline_shed"] += 1
                else:
                    rec["errors"] += 1
                return
            except RpcError:
                rec["errors"] += 1
                return
            rec["lat"].append((loop.time() - intended) * 1000.0)

    t0 = loop.time()
    tasks = []
    for off, tenant in arrivals:
        delay = (t0 + off) - loop.time()
        if delay > 0:
            await asyncio.sleep(delay)
        tasks.append(asyncio.ensure_future(
            one_op(t0 + off, tenant, zipf.next())))
    if tasks:
        await asyncio.wait(tasks)

    out: Dict[str, Dict] = {}
    for tenant, rec in per.items():
        vals = sorted(rec["lat"])
        out[tenant] = {
            "offered_per_sec": tenant_rates[tenant],
            "goodput_per_sec": round(len(vals) / duration, 1),
            "shed": rec["shed"],
            "deadline_shed": rec["deadline_shed"],
            "errors": rec["errors"],
            "p50_ms": round(percentile(vals, 50), 3) if vals else None,
            "p99_ms": round(percentile(vals, 99), 3) if vals else None,
            "p999_ms": round(percentile(vals, 99.9), 3) if vals else None,
            # raw samples ride along so the caller can POOL tenants
            # before taking a p99.9 (per-tenant sample counts are too
            # small for a stable 1-in-1000 quantile); popped before the
            # artifact is written
            "_raw": vals,
        }
    return out


def run_overload_ab(args) -> Dict:
    """The round-19 acceptance bench: three interleaved A/Bs, each arm
    on a FRESH 3-process cluster (armor knobs are process-env, and the
    OFF arm's queue backlog must not leak into the next arm).

    - ``tenant_ab`` — one abusive tenant offered 10x its ops/s quota
      plus well-behaved tenants (within quota), total offered past the
      serving knee, leader-only reads. armor_on children carry
      ``RSTPU_TENANT_OPS``; armor_off children (and the bench-side
      client) run ``RSTPU_TAIL_ARMOR=0``. The gate: the well-behaved
      tenants' pooled p99.9 with armor ON is strictly better than OFF,
      their goodput holds, and the abuser is the one shedding.
    - ``hedge_ab`` — a read-only follower_ok phase against a cluster
      whose replicas have a rare fat tail injected server-side
      (``repl.read=delay_ms`` failpoint via RSTPU_FAILPOINTS, armed at
      child import). RSTPU_HEDGE=1 vs 0 in the BENCH process (hedging
      is client-side). Gates: hedged get p99 strictly better, hedge
      rate within the 5% budget, zero hedges in the off arm.
    - ``overhead_ab`` — the unarmed-overhead guard: NO overload, no
      quotas, mixed get/put at a comfortable rate, RSTPU_TAIL_ARMOR
      1 vs 0 everywhere. Armed-but-idle stamping+checking must cost
      within host noise on the write path (gated as a mean-latency
      ratio bound).
    """
    import shutil
    import tempfile

    from rocksplicator_tpu.rpc.router import ReadPolicy
    from rocksplicator_tpu.utils.stats import Stats

    total_keys = args.shards * args.preload_keys
    quota = float(args.overload_quota)
    abuser_rate = 10.0 * quota
    tenant_rates = {"abuser": abuser_rate}
    good_tenants = [f"good{i}" for i in range(args.overload_good_tenants)]
    for t in good_tenants:
        tenant_rates[t] = float(args.overload_good_rate)
    rep_no = [0]

    def fresh_cluster(root: str, extra_env: Dict[str, str],
                      executor_threads: Optional[int] = None) -> Cluster:
        cluster = Cluster(root, args.shards, args.preload_keys,
                          args.value_bytes, args.write_window,
                          args.read_info_ttl_ms, args.transport,
                          executor_threads or args.executor_threads,
                          extra_env=extra_env)
        cluster.wait_catchup(total_keys)
        return cluster

    def tenant_arm(armor: str):
        name = f"armor_{armor}"

        def run() -> Dict:
            rep_no[0] += 1
            extra_env = ({"RSTPU_TAIL_ARMOR": "1",
                          "RSTPU_TENANT_OPS": str(quota)}
                         if armor == "on"
                         else {"RSTPU_TAIL_ARMOR": "0"})
            root = tempfile.mkdtemp(prefix="rstpu-overload-")
            cluster = None
            try:
                with _bench_env(
                        RSTPU_TAIL_ARMOR="1" if armor == "on" else "0"):
                    Stats.reset_for_test()
                    log(f"overload[{name}]: booting cluster "
                        f"(quota={quota if armor == 'on' else 'none'} "
                        f"ops/s, abuser offered={abuser_rate}/s)")
                    # narrow dispatch on purpose: the overload signal
                    # must come from the abuser monopolizing the
                    # server's executor queue, not from how close the
                    # host's raw CPU knee happens to sit to the
                    # offered rate that day. With one dispatch thread
                    # the OFF arm serializes the flood (queue-wait is
                    # the damage) while the ON arm sheds the abuser
                    # BEFORE dispatch, so the A/B tests the armor.
                    cluster = fresh_cluster(
                        root, extra_env,
                        executor_threads=args.tenant_executor_threads)
                    per_tenant = cluster.ioloop.run_sync(
                        _run_tenant_loop(
                            cluster, tenant_rates,
                            args.overload_duration, total_keys,
                            args.seed + 977 * rep_no[0],
                            args.max_inflight,
                            args.overload_deadline_ms),
                        timeout=args.overload_duration + 180)
                    server = _scrape_counter_sums(
                        cluster, ("rpc.tenant_shed", "rpc.tenant_served",
                                  "rpc.deadline_shed", "rpc.retry_later"))
                good_goodput = round(sum(
                    per_tenant[t]["goodput_per_sec"]
                    for t in good_tenants), 1)
                good_shed = sum(per_tenant[t]["shed"]
                                + per_tenant[t]["deadline_shed"]
                                for t in good_tenants)
                good_pool = sorted(
                    v for t in good_tenants
                    for v in per_tenant[t]["_raw"])
                for rec in per_tenant.values():
                    rec.pop("_raw", None)
                ab = per_tenant["abuser"]
                return {
                    "per_tenant": per_tenant,
                    "good_p999_ms": (round(percentile(good_pool, 99.9), 3)
                                     if good_pool else None),
                    "good_p99_ms": (round(percentile(good_pool, 99), 3)
                                    if good_pool else None),
                    "good_goodput_per_sec": good_goodput,
                    "good_offered_per_sec": round(sum(
                        tenant_rates[t] for t in good_tenants), 1),
                    "good_shed": good_shed,
                    "abuser_offered_per_sec": abuser_rate,
                    "abuser_goodput_per_sec": ab["goodput_per_sec"],
                    "abuser_shed": ab["shed"] + ab["deadline_shed"],
                    "errors": sum(per_tenant[t]["errors"]
                                  for t in per_tenant),
                    "server_counters": server,
                }
            finally:
                if cluster is not None:
                    cluster.stop()
                shutil.rmtree(root, ignore_errors=True)
        return run

    def hedge_arm(hedge: str):
        name = f"hedge_{hedge}"
        inject = (f"repl.read=delay_ms:{args.hedge_inject_ms}:"
                  f"{args.hedge_inject_prob}@seed{args.seed}")

        def run() -> Dict:
            rep_no[0] += 1
            root = tempfile.mkdtemp(prefix="rstpu-overload-")
            cluster = None
            try:
                with _bench_env(RSTPU_TAIL_ARMOR="1",
                                RSTPU_HEDGE=hedge):
                    Stats.reset_for_test()
                    log(f"overload[{name}]: booting cluster "
                        f"(server tail inject {inject})")
                    cluster = fresh_cluster(
                        root, {"RSTPU_FAILPOINTS": inject})
                    phase = run_phase(
                        cluster, ReadPolicy.follower_ok(args.max_lag),
                        args.hedge_read_rate, args.overload_duration,
                        total_keys, args.value_bytes, {"get": 1.0},
                        args.seed + 977 * rep_no[0], args.max_inflight)
                    stats = Stats.get()
                    stats.flush()
                    hedges = stats.get_counter("router.hedges op=get")
                    wins = stats.get_counter("router.hedge_wins op=get")
                    denied = stats.get_counter(
                        "router.hedge_budget_denied op=get")
                g = phase["ops"].get("get") or {}
                reads = g.get("count", 0) + g.get("errors", 0)
                return {
                    "get_p99_ms": g.get("p99_ms"),
                    "get_p50_ms": g.get("p50_ms"),
                    "get_count": g.get("count", 0),
                    "get_errors": g.get("errors", 0),
                    "value_mismatches": phase["value_mismatches"],
                    "hedges": int(hedges),
                    "hedge_wins": int(wins),
                    "hedge_budget_denied": int(denied),
                    "hedge_rate": round(hedges / max(1, reads), 4),
                }
            finally:
                if cluster is not None:
                    cluster.stop()
                shutil.rmtree(root, ignore_errors=True)
        return run

    def overhead_arm(armor: str):
        name = f"armor_{armor}"

        def run() -> Dict:
            rep_no[0] += 1
            root = tempfile.mkdtemp(prefix="rstpu-overload-")
            cluster = None
            try:
                with _bench_env(
                        RSTPU_TAIL_ARMOR="1" if armor == "on" else "0"):
                    Stats.reset_for_test()
                    log(f"overload[overhead {name}]: booting cluster")
                    cluster = fresh_cluster(
                        root,
                        {"RSTPU_TAIL_ARMOR":
                         "1" if armor == "on" else "0"})
                    phase = run_phase(
                        cluster, ReadPolicy.follower_ok(args.max_lag),
                        args.overhead_rate, args.overload_duration,
                        total_keys, args.value_bytes,
                        {"get": 0.5, "put": 0.5},
                        args.seed + 977 * rep_no[0], args.max_inflight)
                g = phase["ops"].get("get") or {}
                pw = phase["ops"].get("put") or {}
                return {
                    "put_mean_ms": pw.get("mean_ms"),
                    "put_p99_ms": pw.get("p99_ms"),
                    "get_mean_ms": g.get("mean_ms"),
                    "get_p99_ms": g.get("p99_ms"),
                    "put_errors": pw.get("errors", 0),
                    "get_errors": g.get("errors", 0),
                    "value_mismatches": phase["value_mismatches"],
                    "achieved_per_sec": phase["achieved_per_sec"],
                }
            finally:
                if cluster is not None:
                    cluster.stop()
                shutil.rmtree(root, ignore_errors=True)
        return run

    return {
        "tenant_ab": run_interleaved(
            [("armor_off", tenant_arm("off")),
             ("armor_on", tenant_arm("on"))],
            reps=args.overload_reps, key="good_p999_ms",
            higher_is_better=False, log=log),
        "hedge_ab": run_interleaved(
            [("hedge_off", hedge_arm("0")), ("hedge_on", hedge_arm("1"))],
            reps=args.overload_reps, key="get_p99_ms",
            higher_is_better=False, log=log),
        "overhead_ab": run_interleaved(
            [("armor_off", overhead_arm("off")),
             ("armor_on", overhead_arm("on"))],
            reps=args.overload_reps, key="put_mean_ms",
            higher_is_better=False, log=log),
    }


def _median_field(samples: List[Dict], field: str) -> Optional[float]:
    from statistics import median

    vals = [s[field] for s in samples or [] if s.get(field) is not None]
    return median(vals) if vals else None


def overload_failures(result: Dict,
                      mechanical_only: bool = False) -> List[str]:
    """The round-19 acceptance gates over the three A/B sections —
    medians across interleaved reps (the ab_runner discipline: per-rep
    comparisons on a drifting host gate the host, not the change).

    ``mechanical_only`` (the smoke's mode) keeps every deterministic
    gate — killswitch arms may not leak typed sheds or hedges, the
    quota must actually bite the abuser, hedges must fire inside their
    5% budget, zero value mismatches, and the armed good-tenant p99
    stays inside a deadline-derived absolute bound — but drops the
    latency-median A/B comparisons: on a 1-rep micro run the serving
    knee itself drifts run to run, so a strict p99.9 comparison gates
    the host, not the armor. The full ``make overload-bench`` runs
    every gate."""
    failures: List[str] = []
    oab = result.get("overload_ab") or {}

    t = oab.get("tenant_ab") or {}
    ts = t.get("samples") or {}
    on_p999 = _median_field(ts.get("armor_on"), "good_p999_ms")
    off_p999 = _median_field(ts.get("armor_off"), "good_p999_ms")
    if on_p999 is None or off_p999 is None:
        failures.append("tenant_ab: missing good-tenant p99.9 in an arm")
    elif not mechanical_only and not on_p999 < off_p999:
        failures.append(
            f"tenant_ab: good p99.9 armor_on {on_p999}ms not strictly "
            f"better than armor_off {off_p999}ms")
    on_good = _median_field(ts.get("armor_on"), "good_goodput_per_sec")
    off_good = _median_field(ts.get("armor_off"), "good_goodput_per_sec")
    if not mechanical_only and on_good is not None \
            and off_good is not None and on_good < 0.8 * off_good:
        failures.append(
            f"tenant_ab: good-tenant goodput collapsed under armor "
            f"({on_good}/s vs {off_good}/s off) — not graceful")
    # deadline enforcement bounds a SUCCESSFUL armed op's latency:
    # anything slower becomes a typed DEADLINE_EXCEEDED instead of a
    # latency sample. 2x the budget leaves room for the open-loop
    # intended-arrival anchor (client dispatch lag precedes the
    # deadline scope), but a p99 past that means the armor isn't
    # converting overload into typed sheds at all.
    budget_ms = (result.get("config") or {}).get("deadline_budget_ms")
    if budget_ms:
        for s in ts.get("armor_on") or []:
            p99 = s.get("good_p99_ms")
            if p99 is not None and p99 > 2.0 * float(budget_ms):
                failures.append(
                    f"tenant_ab: armed good-tenant p99 {p99}ms over "
                    f"the 2x deadline-budget bound "
                    f"({2.0 * float(budget_ms)}ms)")
    for s in ts.get("armor_on") or []:
        if s["abuser_shed"] <= 0:
            failures.append("tenant_ab: armor_on rep shed nothing "
                            "from the abuser")
        if s["abuser_goodput_per_sec"] > 0.35 * s["abuser_offered_per_sec"]:
            failures.append(
                f"tenant_ab: abuser goodput "
                f"{s['abuser_goodput_per_sec']}/s not held near its "
                f"quota (offered {s['abuser_offered_per_sec']}/s)")
    for s in ts.get("armor_off") or []:
        if s["abuser_shed"] + s["good_shed"] > 0:
            failures.append("tenant_ab: armor_off rep shed typed "
                            "errors (killswitch leak)")

    h = oab.get("hedge_ab") or {}
    hs = h.get("samples") or {}
    on_p99 = _median_field(hs.get("hedge_on"), "get_p99_ms")
    off_p99 = _median_field(hs.get("hedge_off"), "get_p99_ms")
    if on_p99 is None or off_p99 is None:
        failures.append("hedge_ab: missing get p99 in an arm")
    elif not mechanical_only and not on_p99 < off_p99:
        failures.append(
            f"hedge_ab: hedged get p99 {on_p99}ms not strictly better "
            f"than unhedged {off_p99}ms")
    for s in hs.get("hedge_on") or []:
        if s["hedges"] <= 0:
            failures.append("hedge_ab: hedge_on rep fired zero hedges")
        # 5% accrual + the small starting-credit transient
        if s["hedge_rate"] > 0.055:
            failures.append(
                f"hedge_ab: hedge rate {s['hedge_rate']} over the "
                f"5% budget")
        if s["value_mismatches"]:
            failures.append("hedge_ab: value mismatches under hedging")
    for s in hs.get("hedge_off") or []:
        if s["hedges"] > 0:
            failures.append("hedge_ab: hedge_off rep fired hedges "
                            "(killswitch leak)")

    o = oab.get("overhead_ab") or {}
    os_ = o.get("samples") or {}
    on_mean = _median_field(os_.get("armor_on"), "put_mean_ms")
    off_mean = _median_field(os_.get("armor_off"), "put_mean_ms")
    if on_mean is None or off_mean is None:
        failures.append("overhead_ab: missing put mean in an arm")
    elif not mechanical_only and off_mean > 0 and on_mean / off_mean > 1.5:
        failures.append(
            f"overhead_ab: armed write-path mean {on_mean}ms vs "
            f"unarmed {off_mean}ms — over the 1.5x host-noise bound")
    for mode, reps_data in os_.items():
        for s in reps_data:
            if s["value_mismatches"]:
                failures.append(f"overhead_ab {mode}: value mismatches")
    return failures


# ---------------------------------------------------------------------------
# hot-shift rebalancer A/B (round 20: the autonomy acceptance number)
# ---------------------------------------------------------------------------


def run_hot_shift_phase(cluster: Cluster, root: str, policy,
                        rebalance_on: bool, args, total_keys: int,
                        seed: int, mix: Dict[str, float]) -> Dict:
    """One 3-window open-loop phase whose zipfian hot set SHIFTS shards
    at the 1/3 mark: ``--hot_frac`` of ops target one hot shard
    (zipfian key popularity WITHIN it), the rest spread uniformly; at
    ``t_shift`` the hot shard flips from 0 to ``shards // 2``. All four
    shard leaders start crammed on node 0 (the macro-bench's static
    layout), so the hot shard rides the most-loaded dispatch queue in
    both arms — until the ON arm's driver notices.

    The ON arm runs the PRODUCTION policy (RebalancerPolicy: EWMA +
    hysteresis + sustain) fed with per-shard dispatched-op rates, and
    actuates each decision with DirectShardMove onto the spare node —
    the same sense→decide→act loop the coordinator-mode Rebalancer
    runs, minus the coordinator. The OFF arm runs no driver. Samples
    are windowed before/settle/after the shift; the A/B gate compares
    the AFTER window's get p99 — the number that says whether the
    policy re-detected and re-homed the NEW hot shard autonomously.

    Correctness rides along: every acked put is read back at the end
    (leader_only) and must return its exact put value — an acked write
    lost across a policy-initiated cutover fails the run, as does any
    mid-run get outside the deterministic preload/put value set."""
    from rocksplicator_tpu.cluster.rebalancer import (RebalancerFlags,
                                                      RebalancerPolicy)
    from rocksplicator_tpu.cluster.shard_move import (DirectMovePlan,
                                                      DirectNode,
                                                      DirectShardMove,
                                                      MoveFlags)
    from rocksplicator_tpu.rpc.errors import RpcError
    from rocksplicator_tpu.utils.segment_utils import segment_to_db_name

    shards = cluster.shards
    duration = float(args.hot_duration)
    keys_per_shard = total_keys // shards
    hot_ref = [0]                # flipped by the shifter mid-run
    h1 = shards // 2             # the post-shift hot shard (≠ 0)
    counts = [0] * shards        # dispatched ops per shard (policy feed)
    rng = random.Random(seed ^ 0x517F7)
    zipf = ZipfianGenerator(keys_per_shard, seed=seed + 2)
    info: Dict = {}

    def gid_source() -> int:
        # hot ops: zipfian rank within the hot shard's keyspace; cold
        # ops: uniform over all shards. gid = k*shards + s keeps the
        # round-robin dealing (shard_of == gid % shards) intact.
        if rng.random() < args.hot_frac:
            s = hot_ref[0]
            k = zipf.next()
        else:
            s = rng.randrange(shards)
            k = rng.randrange(keys_per_shard)
        counts[s] += 1
        return k * shards + s

    def shifter():
        time.sleep(duration)
        info["t_shift"] = time.monotonic()
        hot_ref[0] = h1

    moves: List[Dict] = []
    stop = threading.Event()
    leaders = {s: 0 for s in range(shards)}
    db_to_shard = {segment_to_db_name(SEGMENT, s): s
                   for s in range(shards)}

    def node(i: int) -> DirectNode:
        return DirectNode("127.0.0.1", cluster.admin_ports[i],
                          cluster.ports[i])

    def driver():
        # bench-sized policy knobs: fast EWMA, 2-tick sustain, and a
        # hot_factor low enough that one shard carrying ~hot_frac of a
        # 4-shard fleet clears it; split_factor effectively off (direct
        # mode has no coordinator to host a range split — moves only)
        rp = RebalancerPolicy(RebalancerFlags(
            ewma_alpha=0.5, hot_factor=1.6, cool_factor=1.2, sustain=2,
            max_concurrent=1, split_factor=1e9, min_rate=10.0))
        info["policy"] = rp
        prev = list(counts)
        t_prev = time.monotonic()
        while not stop.wait(0.4):
            cur = list(counts)
            now = time.monotonic()
            dt = max(1e-3, now - t_prev)
            rates = {db: (cur[s] - prev[s]) / dt
                     for db, s in db_to_shard.items()}
            prev, t_prev = cur, now
            for d in rp.observe(rates):
                s = db_to_shard[d.db_name]
                if leaders[s] != 0:
                    # already re-homed; only the spare can take leaders
                    rp.forget(d.db_name)
                    continue
                rec = {"shard": s, "kind": d.kind,
                       "ewma": round(d.ewma, 1),
                       "fleet_mean": round(d.fleet_mean, 1),
                       "after_shift": "t_shift" in info,
                       "t_sec": round(now - info["t0"], 2)}
                try:
                    plan = DirectMovePlan(
                        db_name=d.db_name, source=node(0),
                        target=node(3), leader=node(0),
                        followers=[node(1), node(2)],
                        store_uri=os.path.join(root, "hotshift-bucket"))
                    timings = DirectShardMove(plan, flags=MoveFlags(
                        catchup_lag_threshold=32, catchup_timeout=60.0,
                        cutover_pause_ms=3000.0,
                        poll_interval=0.05)).run()
                except Exception as e:
                    rec.update(ok=False, error=repr(e))
                    moves.append(rec)
                    rp.forget(d.db_name)
                    continue
                leaders[s] = 3
                cluster.apply_move_layout(s, 3)
                rec.update(ok=True, timings_ms=timings)
                moves.append(rec)
                rp.forget(d.db_name)

    sample_log: List = []
    acked_puts: set = set()
    info["t0"] = time.monotonic()
    threads = [threading.Thread(target=shifter, name="hot-shifter",
                                daemon=True)]
    if rebalance_on:
        threads.append(threading.Thread(target=driver,
                                        name="hot-rebalancer",
                                        daemon=True))
    for th in threads:
        th.start()
    res = cluster.ioloop.run_sync(
        _run_open_loop(cluster, policy, args.hot_rate, duration * 3,
                       total_keys, args.value_bytes, mix, seed,
                       args.max_inflight, sample_log=sample_log,
                       gid_source=gid_source, acked_puts=acked_puts),
        timeout=duration * 3 + 240)
    stop.set()
    for th in threads:
        th.join(timeout=150)

    # the acked-write-loss sweep: every key this phase acked a put for
    # must read back its exact put value from the CURRENT leader —
    # wherever the policy moved it
    async def verify_acked() -> List[int]:
        sem = asyncio.Semaphore(64)
        lost: List[int] = []

        async def check(gid: int):
            async with sem:
                for attempt in range(3):
                    try:
                        r = await cluster.router.read(
                            SEGMENT, shard_of(gid, shards), op="get",
                            keys=[key_of(gid)], policy=policy,
                            timeout=15.0)
                    except RpcError:
                        await asyncio.sleep(0.2 * (attempt + 1))
                        continue
                    got = r["values"][0]
                    got = bytes(got) if got is not None else None
                    if got != put_value(gid, args.value_bytes):
                        lost.append(gid)
                    return
                lost.append(gid)  # unreadable counts as lost

        await asyncio.gather(*[check(g) for g in sorted(acked_puts)])
        return sorted(lost)

    lost = cluster.ioloop.run_sync(verify_acked(),
                                   timeout=30 + len(acked_puts))

    t_shift = info.get("t_shift")
    inf = float("inf")
    windows: Dict[str, Dict] = {}
    for name, lo, hi in (
            ("before", -inf, t_shift or inf),
            ("settle", t_shift or inf,
             (t_shift + duration) if t_shift else inf),
            ("after", (t_shift + duration) if t_shift else inf, inf)):
        gets = sorted(lat for ts, op, lat in sample_log
                      if op == "get" and lat is not None and lo <= ts < hi)
        windows[name] = {
            "get_count": len(gets),
            "get_errors": sum(1 for ts, op, lat in sample_log
                              if op == "get" and lat is None
                              and lo <= ts < hi),
            "get_p50_ms": round(percentile(gets, 50), 3) if gets else None,
            "get_p99_ms": round(percentile(gets, 99), 3) if gets else None,
            "put_errors": sum(1 for ts, op, lat in sample_log
                              if op == "put" and lat is None
                              and lo <= ts < hi),
        }
    policy_obj = info.get("policy")
    return {
        "after_get_p99_ms": windows["after"]["get_p99_ms"],
        "after_get_p50_ms": windows["after"]["get_p50_ms"],
        "windows": windows,
        "moves": moves,
        "moves_ok": sum(1 for m in moves if m.get("ok")),
        "moves_after_shift": sum(1 for m in moves
                                 if m.get("ok") and m.get("after_shift")),
        "acked_puts": len(acked_puts),
        "acked_write_losses": len(lost),
        "lost_gids": lost[:20],
        "value_mismatches": res.value_mismatches,
        "achieved_per_sec": res.summarize(
            args.hot_rate, duration * 3)["achieved_per_sec"],
        "policy_snapshot": (policy_obj.snapshot()
                            if policy_obj is not None else None),
    }


def run_hot_shift_ab(args) -> Dict:
    """Interleaved rebalancer-ON vs OFF over the hot-shift workload:
    fresh 4-node cluster (3 replicas + spare, admin plane on) per arm
    per rep — the ON arm's moves rewrite placement, so arms can never
    share a cluster. Lower after-window get p99 wins."""
    import shutil
    import tempfile

    from rocksplicator_tpu.rpc.router import ReadPolicy

    mix = parse_mix(args.hot_mix)
    total_keys = args.shards * args.preload_keys
    # leader_only on purpose: every op for a shard rides its leader's
    # dispatch queue, so placement IS the latency story the A/B tells
    policy = ReadPolicy.leader_only()
    rep_no = [0]

    def arm(on: bool):
        name = "rebalance_on" if on else "rebalance_off"

        def run() -> Dict:
            rep_no[0] += 1
            root = tempfile.mkdtemp(prefix="rstpu-hotshift-")
            cluster = None
            try:
                log(f"hot_shift[{name}]: booting 4-node cluster "
                    f"({args.shards} shards, all leaders on node 0, "
                    f"read stall {args.hot_inject_ms}ms)")
                # symmetric per-read executor stall in BOTH arms: the
                # serving knee is the same everywhere; only WHERE the
                # hot shard's queue lives differs between arms
                extra_env = ({"RSTPU_FAILPOINTS":
                              f"repl.read.serve=delay_ms:"
                              f"{args.hot_inject_ms}"}
                             if args.hot_inject_ms > 0 else {})
                cluster = Cluster(root, args.shards, args.preload_keys,
                                  args.value_bytes, args.write_window,
                                  args.read_info_ttl_ms, args.transport,
                                  args.hot_executor_threads,
                                  with_move_node=True,
                                  extra_env=extra_env)
                cluster.wait_catchup(total_keys)
                return run_hot_shift_phase(
                    cluster, root, policy, on, args, total_keys,
                    args.seed + 271 * rep_no[0], mix)
            finally:
                if cluster is not None:
                    cluster.stop()
                shutil.rmtree(root, ignore_errors=True)
        return name, run

    return run_interleaved([arm(False), arm(True)], reps=args.hot_reps,
                           key="after_get_p99_ms",
                           higher_is_better=False, log=log)


def hot_shift_failures(ab: Dict) -> List[str]:
    """The round-20 autonomy acceptance gates: final-window fleet get
    p99 strictly better with the rebalancer ON (median across
    interleaved reps), zero value mismatches, zero acked-write losses,
    the ON arm demonstrably re-detected the post-shift hot shard (≥1
    successful move AFTER t_shift), and the OFF arm moved nothing."""
    failures: List[str] = []
    samples = ab.get("samples") or {}
    for name in ("rebalance_off", "rebalance_on"):
        if not samples.get(name):
            failures.append(f"no completed {name} rep")
        for s in samples.get(name) or []:
            if s["value_mismatches"]:
                failures.append(
                    f"{name}: {s['value_mismatches']} value mismatches")
            if s["acked_write_losses"]:
                failures.append(
                    f"{name}: {s['acked_write_losses']} acked put(s) "
                    f"did not read back their value after the run "
                    f"(gids {s['lost_gids']})")
            if s["after_get_p99_ms"] is None:
                failures.append(
                    f"{name}: no gets completed in the after window")
    for s in samples.get("rebalance_on") or []:
        if not s["moves_after_shift"]:
            failures.append(
                "rebalance_on rep dispatched no successful move AFTER "
                "the hot-set shift (policy failed to re-detect)")
        for m in s["moves"]:
            if not m.get("ok"):
                failures.append(
                    f"rebalance_on move of shard {m['shard']} failed: "
                    f"{m.get('error')}")
    for s in samples.get("rebalance_off") or []:
        if s["moves"]:
            failures.append("rebalance_off arm executed moves "
                            "(killswitch leak)")
    ratio = (ab.get("ratio_vs_rebalance_off") or {}).get("rebalance_on")
    if ratio is None:
        if not failures:
            failures.append("no ON/OFF after-window p99 ratio computed")
    elif ratio >= 1.0:
        failures.append(
            f"after-window get p99 ON/OFF ratio {ratio} >= 1.0 — the "
            f"rebalancer did not improve the post-shift tail")
    return failures


# ---------------------------------------------------------------------------
# cluster-wide stats scrape (round 14: the spectator-aggregation path)
# ---------------------------------------------------------------------------


def collect_cluster_stats(cluster: Cluster) -> Dict:
    """One spectator-style scrape+merge over the 3 replica processes:
    per-shard read/write rates + max lag, fleet per-op-class p50/p99
    from the exact log-bucket histogram merge."""
    from rocksplicator_tpu.cluster.stats_aggregator import \
        ClusterStatsAggregator

    agg = ClusterStatsAggregator(pool=cluster.pool, ioloop=cluster.ioloop)
    endpoints = [("127.0.0.1", p) for p in cluster.ports]
    return agg.scrape_and_aggregate(endpoints)


def _fleet_p99(cluster_stats: Dict, op: str) -> Optional[float]:
    fam = (cluster_stats.get("fleet_latency_ms") or {}).get(
        "reads.latency_ms") or {}
    rec = fam.get(op)
    return rec.get("p99_ms") if rec else None


def p99_agreement(result: Dict, server_get_ms: List[float]) -> Dict:
    """The acceptance check: the fleet-merged get p99 must AGREE with a
    bench-measured p99 within histogram bucket resolution.

    The apples-to-apples comparison is against the bench's pooled
    SERVER-REPORTED serve times (each read response carries
    ``serve_ms`` — the exact quantity the per-replica
    ``reads.latency_ms`` histograms bucket). The merged value is a
    bucket UPPER edge, so exact agreement means
    fleet_p99 ∈ [bench_p99, bench_p99 * 2^(1/8)]; the gate allows one
    extra bucket step each way for the catch-up probe reads that are in
    the fleet histogram but predate the sweep. The client-side p99
    (intended-arrival → completion) is recorded alongside for the
    queueing-delta picture but only bounds from above."""
    sweep = result.get("sweep") or []
    fleet = _fleet_p99(result.get("cluster_stats") or {}, "get")
    if not sweep or fleet is None or not server_get_ms:
        return {"checked": False}
    bench_server = percentile(sorted(server_get_ms), 99)
    lowest = min(sweep, key=lambda p: p["offered_per_sec"])
    bench_client = (lowest["ops"].get("get") or {}).get("p99_ms")
    bucket_step = 2 ** 0.125  # 8 sub-buckets per octave (~9%)
    tol = bucket_step * bucket_step * 1.01  # two bucket steps + epsilon
    within = (bench_server / tol - 0.05 <= fleet
              <= bench_server * tol + 0.05)
    return {
        "checked": True,
        "bench_server_get_p99_ms": round(bench_server, 3),
        "bench_server_samples": len(server_get_ms),
        "bench_client_get_p99_ms": bench_client,
        "fleet_get_p99_ms": fleet,
        "bucket_step": round(bucket_step, 4),
        "within": within,
        "note": ("fleet p99 is an exact log-bucket merge of the same "
                 "server-side samples (upper-edge convention); client "
                 "p99 adds RTT + open-loop queueing on top"),
    }


# ---------------------------------------------------------------------------
# CDC streaming ingest phase (round 19: kafka wire -> exactly-once
# follower apply with WAL-riding checkpoints + pacing backpressure)
# ---------------------------------------------------------------------------


def _cdc_value(i: int, nbytes: int) -> bytes:
    seed = b"c%d." % i
    return (seed * (nbytes // len(seed) + 1))[:nbytes]


def run_cdc_phase(args, root: str) -> Dict:
    """CDC streaming ingest under serving load, serving-shaped numbers:

    - boots the 3-process churn-profile cluster WITH the admin plane,
      plus a networked BrokerServer in the driver;
    - phase 1 (baseline): the open-loop mixed workload alone;
    - phase 2 (cdc): the same workload while a producer streams CDC
      records into the broker and the leader's IngestionWatchers (one
      per shard, started via the startMessageIngestion admin RPC,
      ``broker://`` transport) apply them through the grouped-commit
      write path — watermark checkpoints riding every batch;
    - a freshness sampler produces marker records and polls a FOLLOWER
      until each is readable: produce -> replicated-readable wall time,
      the end-to-end freshness the artifact reports as p50/p99;
    - after the producer stops, the drain must converge to EXACTLY the
      produced count (``kafka.cdc.records_applied`` delta == produced,
      zero ``dup_skipped``) — the exactly-once invariant, serving-shaped;
    - backpressure must demonstrably engage: the churn engine profile
      builds real flush/L0 debt, so ``kafka.cdc.paced_sleeps``/
      ``paced_ms`` (the delayed-write-controller-derived fetch pacing)
      must be nonzero.
    """
    from rocksplicator_tpu.kafka.network import BrokerServer
    from rocksplicator_tpu.rpc.router import ReadPolicy
    from rocksplicator_tpu.utils.segment_utils import segment_to_db_name

    mix = parse_mix(args.cdc_mix)
    total_keys = args.shards * args.preload_keys
    policy = ReadPolicy.follower_ok(args.max_lag)
    topic = "cdc_bench"
    out: Dict = {}

    cluster = Cluster(
        root, args.shards, args.preload_keys, args.value_bytes,
        args.write_window, args.read_info_ttl_ms, args.transport,
        args.executor_threads, db_profile="churn", with_admin=True)
    broker = None
    try:
        cluster.wait_catchup(total_keys)
        log(f"cdc: baseline phase (no CDC) {args.cdc_serve_rate}/s "
            f"x {args.cdc_duration}s")
        out["baseline"] = run_phase(
            cluster, policy, args.cdc_serve_rate, args.cdc_duration,
            total_keys, args.value_bytes, mix, args.seed, args.max_inflight)

        broker = BrokerServer(
            data_dir=os.path.join(root, "broker")).start()
        bport = broker.port

        async def bcall(method: str, **a):
            return await cluster.pool.call("127.0.0.1", bport, method, a,
                                           timeout=15.0)

        cluster.ioloop.run_sync(
            bcall("broker_create_topic", topic=topic,
                  num_partitions=args.shards), timeout=20)
        for s in range(args.shards):
            db_name = segment_to_db_name(SEGMENT, s)

            async def start(db=db_name):
                return await cluster.pool.call(
                    "127.0.0.1", cluster.admin_ports[0],
                    "start_message_ingestion",
                    {"db_name": db, "topic_name": topic,
                     "kafka_broker_serverset_path":
                         f"broker://127.0.0.1:{bport}"},
                    timeout=30.0)

            cluster.ioloop.run_sync(start(), timeout=35)
        log(f"cdc: {args.shards} IngestionWatchers consuming "
            f"broker://127.0.0.1:{bport} topic={topic}")

        before = _scrape_counter_sums(cluster, ("kafka.cdc.",))
        produced = [0]       # records (producer + markers)
        produced_bytes = [0]
        stop_producing = threading.Event()
        freshness_ms: List[float] = []
        probe_timeouts = [0]

        def producer():
            """Open-loop CDC stream at cdc_rate across all partitions,
            bursts dispatched as one gather per tick (the per-record
            sync-RPC round trip would cap the rate well below target)."""
            i = 0
            t0 = time.monotonic()
            while not stop_producing.is_set():
                due = int((time.monotonic() - t0) * args.cdc_rate)
                burst = min(due - i, 64)
                if burst <= 0:
                    time.sleep(0.005)
                    continue
                msgs = []
                for _ in range(burst):
                    key = b"cdc%08d" % i
                    val = _cdc_value(i, args.cdc_value_bytes)
                    msgs.append((i % args.shards, key, val))
                    produced_bytes[0] += len(key) + len(val)
                    i += 1

                async def send():
                    await asyncio.gather(*[
                        bcall("broker_produce", topic=topic, partition=p,
                              key=k, value=v,
                              timestamp_ms=int(time.time() * 1000))
                        for (p, k, v) in msgs])

                cluster.ioloop.run_sync(send(), timeout=30)
                produced[0] += burst
            # markers ride the same stream: fold them into the total

        def sampler():
            """Produce a marker, poll a FOLLOWER until readable: the
            produce -> replicated-readable freshness distribution."""
            m = 0
            while not stop_producing.is_set():
                shard = m % args.shards
                key = b"cdcmark%06d" % m
                val = _cdc_value(10_000_000 + m, args.cdc_value_bytes)
                t_prod = time.monotonic()
                cluster.ioloop.run_sync(
                    bcall("broker_produce", topic=topic, partition=shard,
                          key=key, value=val,
                          timestamp_ms=int(time.time() * 1000)),
                    timeout=30)
                produced[0] += 1
                produced_bytes[0] += len(key) + len(val)

                async def read():
                    r = await cluster.pool.call(
                        "127.0.0.1", cluster.ports[1], "read",
                        {"db_name": segment_to_db_name(SEGMENT, shard),
                         "op": "get", "keys": [key],
                         "max_lag": 1 << 30}, timeout=5.0)
                    return r["values"][0]

                deadline = time.monotonic() + args.cdc_probe_timeout
                seen = False
                while time.monotonic() < deadline:
                    try:
                        if cluster.ioloop.run_sync(read(), timeout=10) \
                                == val:
                            seen = True
                            break
                    except Exception:
                        pass
                    time.sleep(0.003)
                if seen:
                    freshness_ms.append(
                        (time.monotonic() - t_prod) * 1000.0)
                else:
                    probe_timeouts[0] += 1
                m += 1
                time.sleep(0.1)

        threads = [threading.Thread(target=producer, daemon=True),
                   threading.Thread(target=sampler, daemon=True)]
        t_start = time.monotonic()
        for t in threads:
            t.start()
        log(f"cdc: CDC phase — {args.cdc_rate} rec/s x "
            f"{args.cdc_value_bytes}B CDC stream + {args.cdc_serve_rate}/s"
            f" mixed serving load x {args.cdc_duration}s")
        out["with_cdc"] = run_phase(
            cluster, policy, args.cdc_serve_rate, args.cdc_duration,
            total_keys, args.value_bytes, mix, args.seed + 31,
            args.max_inflight)
        stop_producing.set()
        for t in threads:
            t.join(timeout=30)
        produce_window = time.monotonic() - t_start

        # drain: applied must converge to EXACTLY the produced count
        def applied_delta() -> Dict[str, float]:
            now = _scrape_counter_sums(cluster, ("kafka.cdc.",))
            return {k: now.get(k, 0.0) - before.get(k, 0.0)
                    for k in set(now) | set(before)}

        deadline = time.monotonic() + args.cdc_drain_timeout
        delta = applied_delta()
        while time.monotonic() < deadline and (
                delta.get("kafka.cdc.records_applied", 0) < produced[0]):
            time.sleep(0.25)
            delta = applied_delta()
        drain_sec = time.monotonic() - t_start - produce_window

        for s in range(args.shards):
            db_name = segment_to_db_name(SEGMENT, s)

            async def stop_ing(db=db_name):
                return await cluster.pool.call(
                    "127.0.0.1", cluster.admin_ports[0],
                    "stop_message_ingestion", {"db_name": db},
                    timeout=30.0)

            try:
                cluster.ioloop.run_sync(stop_ing(), timeout=35)
            except Exception:
                pass

        freshness_ms.sort()
        applied = int(delta.get("kafka.cdc.records_applied", 0))
        bytes_applied = delta.get("kafka.cdc.bytes_applied", 0.0)
        out["cdc"] = {
            "produced_records": produced[0],
            "produced_mb": round(produced_bytes[0] / 1e6, 3),
            "applied_records": applied,
            "dup_skipped": int(delta.get("kafka.cdc.dup_skipped", 0)),
            "consumer_errors": int(
                delta.get("kafka.cdc.consumer_errors", 0)),
            "retry_later": int(delta.get("kafka.cdc.retry_later", 0)),
            "apply_batches": int(delta.get("kafka.cdc.batches", 0)),
            "consume_mb_per_sec": round(
                bytes_applied / 1e6 / max(0.001, produce_window + max(
                    0.0, drain_sec)), 3),
            "produce_window_sec": round(produce_window, 2),
            "drain_sec": round(max(0.0, drain_sec), 2),
            "paced_sleeps": int(delta.get("kafka.cdc.paced_sleeps", 0)),
            "paced_ms": round(delta.get("kafka.cdc.paced_ms", 0.0), 1),
            "freshness_samples": len(freshness_ms),
            "freshness_probe_timeouts": probe_timeouts[0],
            "freshness_p50_ms": percentile(freshness_ms, 50.0),
            "freshness_p99_ms": percentile(freshness_ms, 99.0),
        }
        g0 = out["baseline"]["ops"].get("get") or {}
        g1 = out["with_cdc"]["ops"].get("get") or {}
        log(f"cdc: applied {applied}/{produced[0]} "
            f"({out['cdc']['consume_mb_per_sec']} MB/s), freshness "
            f"p99={out['cdc']['freshness_p99_ms']}ms "
            f"({len(freshness_ms)} samples), paced_sleeps="
            f"{out['cdc']['paced_sleeps']}, get p99 "
            f"{g0.get('p99_ms')} -> {g1.get('p99_ms')}ms under CDC")
        return out
    finally:
        if broker is not None:
            broker.stop()
        cluster.stop()


def cdc_failures(result: Dict) -> List[str]:
    """Loud gates for the --cdc artifact (the smoke relies on these)."""
    failures: List[str] = []
    cdc = result.get("cdc_phase", {}).get("cdc") or {}
    if not cdc:
        return ["cdc phase produced no summary"]
    if cdc["applied_records"] != cdc["produced_records"]:
        failures.append(
            f"exactly-once violated: applied {cdc['applied_records']} != "
            f"produced {cdc['produced_records']} after drain")
    if cdc["dup_skipped"]:
        failures.append(
            f"{cdc['dup_skipped']} duplicate offsets skipped in a "
            f"crash-free run (consumer re-fetched acked records)")
    if not cdc["paced_sleeps"]:
        failures.append(
            "backpressure never engaged (kafka.cdc.paced_sleeps == 0 "
            "under the churn profile)")
    if not cdc["freshness_samples"]:
        failures.append("no freshness samples completed")
    if cdc["freshness_probe_timeouts"] > cdc["freshness_samples"]:
        failures.append(
            f"freshness probes mostly timed out "
            f"({cdc['freshness_probe_timeouts']} timeouts vs "
            f"{cdc['freshness_samples']} samples)")
    base = result.get("cdc_phase", {}).get("baseline", {})
    with_cdc = result.get("cdc_phase", {}).get("with_cdc", {})
    for name, phase in (("baseline", base), ("with_cdc", with_cdc)):
        g = (phase.get("ops") or {}).get("get") or {}
        if not g.get("count"):
            failures.append(f"no reads completed in the {name} phase")
    return failures


# ---------------------------------------------------------------------------
# main
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    # child modes
    p.add_argument("--serve", choices=["leader", "follower", "topo"])
    p.add_argument("--topo",
                   help="serve: JSON [[shard, role, upstream_port], ...] "
                        "— this node's hosted subset of the fleet "
                        "topology (fleet_bench spawns these)")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--upstream_port", type=int, default=0)
    p.add_argument("--admin_port", type=int, default=0,
                   help="serve: also run the Admin RPC plane on this "
                        "port (required for mid-bench shard moves)")
    p.add_argument("--db_dir")
    p.add_argument("--ab_worker", choices=["leader_only", "follower_ok"])
    p.add_argument("--ports", help="ab_worker: leader,f1,f2 ports")
    p.add_argument("--db_profile", default="default",
                   choices=["default", "churn"],
                   help="serve: engine options profile (churn = small "
                        "memtables + low L0 triggers for compaction-"
                        "pressure benches)")
    # shared topology / workload knobs
    p.add_argument("--shards", type=int, default=4)
    p.add_argument("--preload_keys", type=int, default=2000,
                   help="keys preloaded PER SHARD before the timed phases")
    p.add_argument("--value_bytes", type=int, default=128)
    p.add_argument("--write_window", type=int, default=64)
    p.add_argument("--read_info_ttl_ms", type=int, default=1500)
    p.add_argument("--executor_threads", type=int, default=4)
    # driver knobs
    p.add_argument("--rates", default="300,600,1200",
                   help="offered-throughput sweep points (ops/sec)")
    p.add_argument("--duration", type=float, default=5.0,
                   help="seconds per sweep point")
    p.add_argument("--mix", default=DEFAULT_MIX)
    p.add_argument("--read_policy", default="follower_ok",
                   choices=["leader_only", "follower_ok", "nearest"])
    p.add_argument("--max_lag", type=int, default=128,
                   help="staleness bound (seqs) for follower_ok/nearest")
    p.add_argument("--seed", type=int, default=1)
    p.add_argument("--max_inflight", type=int, default=512)
    p.add_argument("--transport", default="tcp", choices=["tcp", "uds"])
    p.add_argument("--ab", action="store_true",
                   help="run the leader_only vs follower_ok read A/B")
    p.add_argument("--ab_duration", type=float, default=5.0)
    p.add_argument("--ab_readers", type=int, default=8,
                   help="concurrent reader coroutines per worker process")
    p.add_argument("--ab_procs", type=int, default=0,
                   help="A/B client fleet size (worker PROCESSES per "
                        "variant; 0 = derive from cpu count)")
    p.add_argument("--ab_reps", type=int, default=3)
    p.add_argument("--move_mid_bench", action="store_true",
                   help="spawn a 4th (spare) node and run one LIVE "
                        "shard move (shard 0's leader onto it) in the "
                        "middle of a 3-window phase, recording get p99 "
                        "before/during/after the flip")
    p.add_argument("--move_rate", type=float, default=0.0,
                   help="offered ops/s for the move phase (0 = first "
                        "sweep rate)")
    p.add_argument("--sched_ab", action="store_true",
                   help="standalone mode: interleaved A/B of the "
                        "workload-adaptive compaction scheduler "
                        "(RSTPU_COMPACTION_SCHED=1 vs 0) over fresh "
                        "churn-profile clusters under a write-heavy mix")
    p.add_argument("--sched_rate", type=float, default=900.0)
    p.add_argument("--sched_duration", type=float, default=8.0)
    p.add_argument("--sched_reps", type=int, default=2)
    p.add_argument("--sched_mix", default="get=0.5,put=0.5")
    p.add_argument("--overload_ab", action="store_true",
                   help="standalone mode: the round-19 tail-armor "
                        "acceptance A/Bs (per-tenant admission under "
                        "an abusive tenant, hedged follower reads "
                        "against an injected server tail, and the "
                        "unarmed-overhead guard), fresh cluster per arm")
    p.add_argument("--overload_quota", type=float, default=200.0,
                   help="per-tenant ops/s quota (RSTPU_TENANT_OPS) in "
                        "the armor_on arm; the abuser offers 10x this")
    p.add_argument("--overload_good_rate", type=float, default=130.0,
                   help="offered ops/s per well-behaved tenant "
                        "(must sit under the quota)")
    p.add_argument("--overload_good_tenants", type=int, default=3)
    p.add_argument("--tenant_executor_threads", type=int, default=1,
                   help="executor threads per server in the tenant "
                        "A/B only (default 1: the abuser flood must "
                        "monopolize an explicit dispatch queue, not "
                        "race the host's raw CPU knee — the armor "
                        "sheds BEFORE dispatch, so the contrast is "
                        "structural, not host-dependent)")
    p.add_argument("--overload_duration", type=float, default=6.0,
                   help="seconds per overload/hedge/overhead phase")
    p.add_argument("--overload_reps", type=int, default=3)
    p.add_argument("--overload_deadline_ms", type=float, default=2000.0,
                   help="client deadline budget stamped on every "
                        "tenant-phase op (armor_on arm)")
    p.add_argument("--hedge_read_rate", type=float, default=400.0,
                   help="offered get/s for the hedge A/B phase")
    p.add_argument("--hedge_inject_ms", type=int, default=80,
                   help="server-side injected read delay (the fat "
                        "tail hedging should cut)")
    p.add_argument("--hedge_inject_prob", type=float, default=0.025,
                   help="probability of the injected delay per read "
                        "(rare: the p95-derived hedge delay must stay "
                        "UNDER the injected tail, or hedges fire too "
                        "late to rescue it)")
    p.add_argument("--overhead_rate", type=float, default=500.0,
                   help="offered ops/s for the unarmed-overhead A/B "
                        "(comfortably under the knee)")
    p.add_argument("--hot_shift", action="store_true",
                   help="standalone mode: interleaved rebalancer-ON vs "
                        "OFF A/B over a workload whose zipfian hot set "
                        "SHIFTS shards mid-run; the ON arm drives the "
                        "production RebalancerPolicy with "
                        "DirectShardMove as actuator; gates: final-"
                        "window get p99 ON < OFF, zero value "
                        "mismatches, zero acked-write loss")
    p.add_argument("--hot_rate", type=float, default=520.0,
                   help="offered ops/s for the hot-shift phase: with "
                        "the default 3ms read stall the all-on-node-0 "
                        "arm offers ~390 gets/s against a ~300/s "
                        "single-executor knee (overloaded), while the "
                        "rebalanced end-state's hottest node sits at "
                        "~260 gets/s (under it)")
    p.add_argument("--hot_frac", type=float, default=0.55,
                   help="fraction of ops targeting the hot shard")
    p.add_argument("--hot_duration", type=float, default=6.0,
                   help="seconds per hot-shift window (3 windows: "
                        "before/settle/after; shift at the 1/3 mark)")
    p.add_argument("--hot_reps", type=int, default=2)
    p.add_argument("--hot_mix", default="get=0.75,put=0.25",
                   help="op mix for the hot-shift phase")
    p.add_argument("--hot_executor_threads", type=int, default=1,
                   help="executor threads per server in the hot-shift "
                        "A/B (default 1: the hot shard must monopolize "
                        "an explicit dispatch queue — the same "
                        "structural-knee discipline as the tenant A/B)")
    p.add_argument("--hot_inject_ms", type=int, default=3,
                   help="server-side executor-occupancy stall per read "
                        "(repl.read.serve failpoint, BOTH arms): makes "
                        "the per-process serving knee rate-derived "
                        "(~1000/ms gets/s) instead of host-derived, so "
                        "the A/B contrast is placement, even on a "
                        "1-core host where CPU is zero-sum across "
                        "server processes")
    p.add_argument("--cdc", action="store_true",
                   help="CDC streaming-ingest phase: baseline mixed "
                        "phase, then the same load while a producer "
                        "streams into a networked broker and the "
                        "leader's IngestionWatchers apply exactly-once "
                        "with WAL-riding checkpoints; artifact gates on "
                        "applied==produced, backpressure engaging, and "
                        "follower-readable freshness samples")
    p.add_argument("--cdc_rate", type=float, default=600.0,
                   help="CDC records/s offered to the broker")
    p.add_argument("--cdc_value_bytes", type=int, default=256)
    p.add_argument("--cdc_duration", type=float, default=8.0,
                   help="seconds per phase (baseline and with-CDC)")
    p.add_argument("--cdc_serve_rate", type=float, default=400.0,
                   help="foreground mixed ops/s during both phases")
    p.add_argument("--cdc_mix", default="get=0.7,put=0.3")
    p.add_argument("--cdc_probe_timeout", type=float, default=15.0,
                   help="per-marker freshness probe deadline (s)")
    p.add_argument("--cdc_drain_timeout", type=float, default=90.0,
                   help="post-produce drain deadline (s)")
    p.add_argument("--overload_gates", choices=("full", "mechanical"),
                   default="full",
                   help="'full' (default) gates the latency medians "
                        "too; 'mechanical' (the smoke) keeps only the "
                        "deterministic gates — killswitch leaks, quota "
                        "bite, hedge budget, value mismatches — since "
                        "a 1-rep micro run's serving knee drifts too "
                        "much for a strict p99.9 comparison")
    p.add_argument("--out", help="write the artifact JSON here")
    args = p.parse_args(argv)

    if args.serve:
        if not args.db_dir:
            p.error("--serve requires --db_dir")
        return serve(args)
    if args.ab_worker:
        if not args.ports:
            p.error("--ab_worker requires --ports")
        return ab_worker(args)
    if args.ab_procs <= 0:
        # enough client fleet that the SERVERS saturate first: the 3
        # replica processes want ~3 cores + headroom, the fleet gets the
        # rest. On a small (2-4 core) CI host this bottoms out at 2 and
        # the client side caps the measured ratio — the roofline caveat
        # PERF.md round 13 documents.
        args.ab_procs = max(2, min(16, (os.cpu_count() or 4) - 8))

    import shutil
    import tempfile

    from rocksplicator_tpu.rpc.router import ReadPolicy

    mix = parse_mix(args.mix)
    rates = [float(r) for r in args.rates.split(",") if r]
    total_keys = args.shards * args.preload_keys
    policy = {
        "leader_only": ReadPolicy.leader_only(),
        "follower_ok": ReadPolicy.follower_ok(args.max_lag),
        "nearest": ReadPolicy.nearest(args.max_lag),
    }[args.read_policy]

    root = tempfile.mkdtemp(prefix="rstpu-macro-")
    t0 = time.monotonic()
    if args.cdc:
        # standalone mode: the churn cluster + admin plane + broker
        # belong to the CDC phase runner
        result = {
            "bench": "macro_bench_cdc",
            "config": {
                "shards": args.shards,
                "preload_keys_per_shard": args.preload_keys,
                "value_bytes": args.value_bytes,
                "mix": parse_mix(args.cdc_mix),
                "serve_rate": args.cdc_serve_rate,
                "cdc_rate": args.cdc_rate,
                "cdc_value_bytes": args.cdc_value_bytes,
                "duration": args.cdc_duration,
                "max_lag": args.max_lag,
                "transport": args.transport,
                "seed": args.seed,
                "db_profile": "churn",
                "topology": ("1 leader + 2 followers (mode 1), 3 OS "
                             "processes + driver-hosted BrokerServer; "
                             "IngestionWatcher per shard on the leader "
                             "via startMessageIngestion"),
            },
            "host_calibration": host_calibration(root),
        }
        try:
            result["cdc_phase"] = run_cdc_phase(args, root)
        finally:
            shutil.rmtree(root, ignore_errors=True)
        result["elapsed_sec"] = round(time.monotonic() - t0, 1)
        result["failures"] = cdc_failures(result)
        return emit_gated_artifact(result, args.out, "macro_bench", log)
    if args.sched_ab:
        # standalone mode: each arm boots its own cluster (the
        # scheduler switch is a process-env knob), so the normal
        # shared-cluster flow below does not apply
        result = {
            "bench": "macro_bench_sched_ab",
            "config": {
                "shards": args.shards,
                "preload_keys_per_shard": args.preload_keys,
                "value_bytes": args.value_bytes,
                "mix": parse_mix(args.sched_mix),
                "rate": args.sched_rate,
                "duration": args.sched_duration,
                "reps": args.sched_reps,
                "transport": args.transport,
                "seed": args.seed,
                "db_profile": "churn",
                "topology": ("1 leader + 2 followers (mode 1), "
                             "3 OS processes, fresh cluster per arm"),
            },
            "host_calibration": host_calibration(root),
        }
        try:
            result["sched_ab"] = run_sched_ab(args)
        finally:
            shutil.rmtree(root, ignore_errors=True)
        result["elapsed_sec"] = round(time.monotonic() - t0, 1)
        result["failures"] = sched_ab_failures(
            result["sched_ab"]["samples"],
            picks_of=lambda s: s["compaction.sched_picks"])
        return emit_gated_artifact(result, args.out, "macro_bench", log)
    if args.hot_shift:
        # standalone mode: fresh 4-node cluster per arm per rep (the
        # ON arm's policy-driven moves rewrite placement)
        result = {
            "bench": "macro_bench_hot_shift",
            "config": {
                "shards": args.shards,
                "preload_keys_per_shard": args.preload_keys,
                "value_bytes": args.value_bytes,
                "mix": parse_mix(args.hot_mix),
                "rate": args.hot_rate,
                "hot_frac": args.hot_frac,
                "window_duration": args.hot_duration,
                "shift_at": "t0 + window_duration (hot shard 0 -> "
                            f"{args.shards // 2})",
                "reps": args.hot_reps,
                "executor_threads": args.hot_executor_threads,
                "read_stall_ms": args.hot_inject_ms,
                "read_policy": "leader_only",
                "transport": args.transport,
                "seed": args.seed,
                "topology": ("1 leader + 2 followers + spare "
                             "(mode 1), 4 OS processes, fresh cluster "
                             "per arm"),
            },
            "host_calibration": host_calibration(root),
        }
        try:
            result["hot_shift_ab"] = run_hot_shift_ab(args)
        finally:
            shutil.rmtree(root, ignore_errors=True)
        result["elapsed_sec"] = round(time.monotonic() - t0, 1)
        result["failures"] = hot_shift_failures(result["hot_shift_ab"])
        return emit_gated_artifact(result, args.out, "macro_bench", log)
    if args.overload_ab:
        # standalone mode: every arm boots its own cluster (the armor
        # switches are process-env knobs on BOTH sides of the wire)
        result = {
            "bench": "macro_bench_overload_ab",
            "config": {
                "shards": args.shards,
                "preload_keys_per_shard": args.preload_keys,
                "value_bytes": args.value_bytes,
                "tenant_quota_ops": args.overload_quota,
                "abuser_offered_per_sec": 10.0 * args.overload_quota,
                "good_tenants": args.overload_good_tenants,
                "good_rate_per_tenant": args.overload_good_rate,
                "tenant_executor_threads": args.tenant_executor_threads,
                "deadline_budget_ms": args.overload_deadline_ms,
                "hedge_read_rate": args.hedge_read_rate,
                "hedge_inject": (f"{args.hedge_inject_ms}ms @ "
                                 f"p={args.hedge_inject_prob}"),
                "overhead_rate": args.overhead_rate,
                "duration": args.overload_duration,
                "reps": args.overload_reps,
                "max_lag": args.max_lag,
                "transport": args.transport,
                "seed": args.seed,
                "gates": args.overload_gates,
                "topology": ("1 leader + 2 followers (mode 1), "
                             "3 OS processes, fresh cluster per arm"),
            },
            "host_calibration": host_calibration(root),
        }
        try:
            result["overload_ab"] = run_overload_ab(args)
        finally:
            shutil.rmtree(root, ignore_errors=True)
        result["elapsed_sec"] = round(time.monotonic() - t0, 1)
        result["failures"] = overload_failures(
            result, mechanical_only=args.overload_gates == "mechanical")
        return emit_gated_artifact(result, args.out, "macro_bench", log)
    result: Dict = {
        "bench": "macro_bench",
        "config": {
            "shards": args.shards,
            "preload_keys_per_shard": args.preload_keys,
            "total_keys": total_keys,
            "value_bytes": args.value_bytes,
            "mix": mix,
            "read_policy": args.read_policy,
            "max_lag": args.max_lag,
            "transport": args.transport,
            "seed": args.seed,
            "topology": "1 leader + 2 followers (mode 1), 3 OS processes",
        },
    }
    cluster = None
    try:
        log(f"macro_bench: spawning 3-replica cluster "
            f"({args.shards} shards, {total_keys} keys)")
        cluster = Cluster(root, args.shards, args.preload_keys,
                          args.value_bytes, args.write_window,
                          args.read_info_ttl_ms, args.transport,
                          args.executor_threads,
                          with_move_node=args.move_mid_bench)
        cluster.wait_catchup(total_keys)
        result["host_calibration"] = host_calibration(root)
        sweep = []
        server_get_ms: List[float] = []
        for i, rate in enumerate(rates):
            log(f"macro_bench: sweep {i + 1}/{len(rates)} "
                f"offered={rate}/s x {args.duration}s "
                f"policy={args.read_policy}")
            point = run_phase(cluster, policy, rate, args.duration,
                              total_keys, args.value_bytes, mix,
                              args.seed + i * 101, args.max_inflight,
                              server_get_sink=server_get_ms)
            sweep.append(point)
            g = point["ops"].get("get") or {}
            log(f"  achieved={point['achieved_per_sec']}/s "
                f"get p50={g.get('p50_ms')}ms p99={g.get('p99_ms')}ms "
                f"roles={point['reads_by_role']}")
        result["sweep"] = sweep
        # round 14: the cluster-wide metrics plane's view of the same
        # run — scrape every replica's `stats` RPC through the SAME
        # aggregator the spectator's scrape loop uses and merge exactly
        # (log-bucket histograms add losslessly). Taken right after the
        # sweep so the A/B's saturation reads don't swamp the op-class
        # histograms the agreement check compares.
        result["cluster_stats"] = collect_cluster_stats(cluster)
        result["p99_agreement"] = p99_agreement(result, server_get_ms)
        log(f"  cluster_stats: {result['cluster_stats']['replicas_scraped']}"
            f" replicas, max_lag="
            f"{result['cluster_stats']['max_replication_lag']}, "
            f"fleet get p99="
            f"{_fleet_p99(result['cluster_stats'], 'get')}ms vs bench "
            f"server-side "
            f"{result['p99_agreement'].get('bench_server_get_p99_ms')}ms "
            f"(within={result['p99_agreement'].get('within')})")
        if args.move_mid_bench:
            move_rate = args.move_rate or rates[0]
            log(f"macro_bench: LIVE shard move mid-bench (shard 0 "
                f"leader -> spare node) under {move_rate}/s mixed load")
            result["shard_move"] = run_move_phase(
                cluster, root, policy, move_rate, args.duration,
                total_keys, args.value_bytes, mix, args.seed + 9001,
                args.max_inflight)
            result["config"]["move_mid_bench"] = True
            mv = result["shard_move"]
            w = mv["windows"]
            log(f"  move ok={mv['move'].get('ok')} "
                f"phases={mv['move'].get('timings_ms')} — get p99 "
                f"before/during/after = {w['before']['get_p99_ms']}/"
                f"{w['during']['get_p99_ms']}/{w['after']['get_p99_ms']}"
                f" ms (put errors during: {w['during']['put_errors']})")
        if args.ab:
            log(f"macro_bench: read A/B leader_only vs follower_ok"
                f"(max_lag={args.max_lag}) x {args.ab_reps} reps, "
                f"{args.ab_procs} worker procs x {args.ab_readers} readers")
            result["read_ab"] = run_read_ab(
                cluster, args.max_lag, args.ab_duration, args.shards,
                args.preload_keys, args.ab_readers, args.ab_procs,
                args.ab_reps, args.seed, args.transport)
            result["config"]["ab_procs"] = args.ab_procs
            result["config"]["ab_readers"] = args.ab_readers
    finally:
        if cluster is not None:
            cluster.stop()
        shutil.rmtree(root, ignore_errors=True)
    result["elapsed_sec"] = round(time.monotonic() - t0, 1)

    # loud failure gates (the smoke target relies on these)
    failures: List[str] = []
    for point in result.get("sweep", []):
        if point["value_mismatches"]:
            failures.append(
                f"{point['value_mismatches']} get(s) returned a value "
                f"outside the deterministic preload/put set at "
                f"offered={point['offered_per_sec']}")
    if not result.get("sweep"):
        failures.append("empty sweep")
    total_reads = sum(
        sum(p["ops"].get(op, {}).get("count", 0)
            for op in ("get", "multi_get", "scan"))
        for p in result.get("sweep", []))
    if total_reads == 0:
        failures.append("no reads completed in any sweep point")
    if (args.read_policy == "follower_ok"
            and not any(p["reads_by_role"].get("FOLLOWER")
                        for p in result.get("sweep", []))):
        failures.append("follower_ok policy but zero follower-served reads")
    cs = result.get("cluster_stats") or {}
    if not cs.get("per_shard"):
        failures.append("cluster_stats scrape returned no per-shard series")
    elif cs.get("replicas_scraped", 0) < 3:
        failures.append(
            f"cluster_stats scraped only {cs.get('replicas_scraped')}/3 "
            f"replicas")
    if args.move_mid_bench:
        mv = result.get("shard_move") or {}
        if not (mv.get("move") or {}).get("ok"):
            failures.append(
                f"mid-bench shard move failed: "
                f"{(mv.get('move') or {}).get('error')}")
        else:
            w = mv["windows"]
            if not w["during"]["get_count"]:
                failures.append("no reads served DURING the live move")
            if not w["after"]["get_count"] or not w["after"]["put_count"]:
                failures.append(
                    "reads/writes did not resume after the move flip")
    agr = result.get("p99_agreement") or {}
    if agr.get("checked") and not agr.get("within"):
        failures.append(
            f"fleet-merged get p99 {agr['fleet_get_p99_ms']}ms disagrees "
            f"with bench-measured server-side "
            f"{agr['bench_server_get_p99_ms']}ms beyond histogram bucket "
            f"resolution")
    result["failures"] = failures

    out_json = json.dumps(result, indent=2, sort_keys=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out_json + "\n")
        log(f"macro_bench: artifact -> {args.out}")
    print(out_json)
    if failures:
        for msg in failures:
            log(f"macro_bench: FAILURE: {msg}")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
