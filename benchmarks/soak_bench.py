#!/usr/bin/env python
"""Scale/soak benchmark — BASELINE config #5 shape at hundreds of shards.

Two phases, each recording hard numbers into a JSON result file (the
VERDICT round-2 "scale evidence" artifact; BASELINE.md targets table):

1. **shard-scale storm** — N shard DBs (default 256) in one process with
   tiny memtables + aggressive L0 triggers so flush/compaction run
   continuously, W writer + R reader threads sweeping all shards for T
   seconds. Records write/read throughput and the
   ``storage.write_stall_ms`` histogram (p99 target: < 10 ms).
2. **cluster failover under load** — 3 nodes × M shards (default 32)
   with semi-sync replication, mixed writes during a leader crash;
   records re-election convergence time and acked-write loss fraction.

Usage:
    python -m benchmarks.soak_bench [--shards 256] [--storm_sec 60]
        [--cluster_shards 32] [--out benchmarks/results/soak.json]

Reference precedent for harness shape: performance.cpp (N shards × M
writer threads, reports bytes/s) and the gated admin integration tests
(/root/reference/rocksdb_replicator/performance.cpp:57-66,
rocksdb_admin/tests/admin_handler_test.cpp).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def storm_phase(n_shards: int, storm_sec: float, writers: int,
                readers: int, value_bytes: int) -> dict:
    """Phase 1: flush/compaction storm across n_shards real engines."""
    from rocksplicator_tpu.storage.engine import DB, DBOptions
    from rocksplicator_tpu.storage.merge import UInt64AddOperator
    from rocksplicator_tpu.utils.stats import Stats

    Stats.reset_for_test()
    root = tempfile.mkdtemp(prefix="rstpu-soak-")
    opts = DBOptions(
        memtable_bytes=48 << 10,
        level0_compaction_trigger=3,
        background_compaction=True,
        merge_operator=UInt64AddOperator(),
    )
    t0 = time.monotonic()
    dbs = [DB(os.path.join(root, f"s{i:05d}"), opts)
           for i in range(n_shards)]
    open_sec = time.monotonic() - t0
    log(f"opened {n_shards} shard DBs in {open_sec:.1f}s "
        f"({2 * n_shards} bg threads)")

    stop = threading.Event()
    counts = {"writes": 0, "reads": 0, "read_hits": 0, "errors": 0}
    lock = threading.Lock()
    val = b"v" * value_bytes

    def writer(tid: int) -> None:
        w = r = 0
        i = tid
        try:
            while not stop.is_set():
                db = dbs[i % n_shards]
                key = f"w{tid}-k{(i // n_shards) % 4096:06d}".encode()
                if i % 7 == 0:
                    db.merge(key, b"\x01\x00\x00\x00\x00\x00\x00\x00")
                else:
                    db.put(key, val)
                w += 1
                i += writers
        except Exception as e:  # pragma: no cover - diagnostics
            log(f"writer {tid} died: {e!r}")
            with lock:
                counts["errors"] += 1
        with lock:
            counts["writes"] += w
            counts["reads"] += r

    def reader(tid: int) -> None:
        r = hits = 0
        i = tid
        try:
            while not stop.is_set():
                db = dbs[i % n_shards]
                key = f"w{tid % writers}-k{(i // n_shards) % 4096:06d}".encode()
                if db.get(key) is not None:
                    hits += 1
                r += 1
                i += readers
        except Exception as e:  # pragma: no cover - diagnostics
            log(f"reader {tid} died: {e!r}")
            with lock:
                counts["errors"] += 1
        with lock:
            counts["reads"] += r
            counts["read_hits"] += hits

    threads = [threading.Thread(target=writer, args=(t,), daemon=True)
               for t in range(writers)]
    threads += [threading.Thread(target=reader, args=(t,), daemon=True)
                for t in range(readers)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    time.sleep(storm_sec)
    stop.set()
    for t in threads:
        t.join(30)
    elapsed = time.monotonic() - t0
    stats = Stats.get()
    stall_p99 = stats.metric_percentile("storage.write_stall_ms", 99)
    stall_max = stats.metric_percentile("storage.write_stall_ms", 100)
    stall_n = stats.metric_count("storage.write_stall_ms")
    t0 = time.monotonic()
    for db in dbs:
        db.close()
    close_sec = time.monotonic() - t0
    shutil.rmtree(root, ignore_errors=True)
    result = {
        "shards": n_shards,
        "storm_sec": round(elapsed, 1),
        "writer_threads": writers,
        "reader_threads": readers,
        "writes": counts["writes"],
        "reads": counts["reads"],
        "read_hit_rate": round(
            counts["read_hits"] / max(1, counts["reads"]), 3),
        "errors": counts["errors"],
        "writes_per_sec": round(counts["writes"] / elapsed),
        "reads_per_sec": round(counts["reads"] / elapsed),
        "write_stall_p99_ms": round(stall_p99, 3),
        "write_stall_max_ms": round(stall_max, 3),
        "write_stall_samples": stall_n,
        "open_sec": round(open_sec, 1),
        "close_sec": round(close_sec, 1),
    }
    log(f"storm: {json.dumps(result)}")
    return result


def failover_phase(n_shards: int, load_sec: float) -> dict:
    """Phase 2: leader crash under write load, 3 nodes, semi-sync."""
    from tests.test_cluster import ServiceNode, wait_until
    from rocksplicator_tpu.cluster.controller import Controller
    from rocksplicator_tpu.cluster.coordinator import CoordinatorServer
    from rocksplicator_tpu.cluster.model import ResourceDef
    from rocksplicator_tpu.storage import DBOptions, WriteBatch
    from rocksplicator_tpu.utils.dbconfig import DBConfigManager
    from rocksplicator_tpu.utils.segment_utils import segment_to_db_name

    import pathlib

    tmp = tempfile.mkdtemp(prefix="rstpu-soak-cluster-")
    tmp_path = pathlib.Path(tmp)
    coord = CoordinatorServer(port=0, session_ttl=1.5)
    DBConfigManager.get().load_from_dict({"seg": {"replication_mode": 1}})
    nodes = [ServiceNode(tmp_path, n, coord.port, "soak")
             for n in ("a", "b", "c")]
    for node in nodes:
        node.handler._options_gen = lambda seg: DBOptions(
            memtable_bytes=64 * 1024, level0_compaction_trigger=3,
            background_compaction=True,
        )
    ctrl = Controller("127.0.0.1", coord.port, "soak", "ctrl",
                      reconcile_interval=0.3)
    ctrl.add_resource(ResourceDef("seg", num_shards=n_shards, replicas=3))

    def leaders():
        out = {}
        for s in range(n_shards):
            for n in nodes:
                if n.participant.current_states.get(f"seg_{s}") in (
                        "LEADER", "MASTER"):
                    out[s] = n
        return out

    stop = threading.Event()
    written = [0]
    errors = [0]
    lock = threading.Lock()
    result: dict = {"cluster_shards": n_shards}
    threads = []
    try:
        ok = wait_until(lambda: len(leaders()) == n_shards, timeout=120)
        if not ok:
            result["error"] = "initial leader election incomplete"
            return result

        def writer(tid):
            i = 0
            while not stop.is_set():
                shard = i % n_shards
                ldr = leaders().get(shard)
                if ldr is None:
                    time.sleep(0.02)
                    continue
                app = ldr.handler.db_manager.get_db(
                    segment_to_db_name("seg", shard))
                if app is None:
                    time.sleep(0.02)
                    continue
                try:
                    app.write(WriteBatch().put(
                        f"t{tid}-{i:08d}".encode(), b"v" * 128))
                    with lock:
                        written[0] += 1
                except Exception:
                    with lock:
                        errors[0] += 1
                i += 1

        threads = [threading.Thread(target=writer, args=(t,), daemon=True)
                   for t in range(4)]
        for t in threads:
            t.start()
        time.sleep(load_sec / 2)
        by_node = {}
        for s, n in leaders().items():
            by_node.setdefault(n.name, []).append(s)
        victim = max(nodes, key=lambda n: len(by_node.get(n.name, [])))
        led = len(by_node.get(victim.name, []))
        t0 = time.monotonic()
        victim.stop(graceful=False)
        nodes.remove(victim)
        reelected = wait_until(lambda: len(leaders()) == n_shards,
                               timeout=120)
        reelect_sec = time.monotonic() - t0
        time.sleep(load_sec / 2)
        stop.set()
        for t in threads:
            t.join(30)

        def converged():
            for s in range(n_shards):
                seqs = set()
                for n in nodes:
                    app = n.handler.db_manager.get_db(
                        segment_to_db_name("seg", s))
                    if app is not None:
                        seqs.add(app.latest_sequence_number())
                if len(seqs) > 1:
                    return False
            return True

        conv = wait_until(converged, timeout=120)
        if not conv:
            # per-shard forensics: which shards diverge, each replica's
            # seq + the ReplicatedDB's own view (role/upstream/acked) —
            # the data needed to tell a stalled pull loop from a
            # mis-pointed upstream from a dead task
            divergent = {}
            for s in range(n_shards):
                db_name = segment_to_db_name("seg", s)
                seqs = {}
                intro = {}
                for n in nodes:
                    app = n.handler.db_manager.get_db(db_name)
                    if app is not None:
                        seqs[n.name] = app.latest_sequence_number()
                    rdb = n.replicator.get_db(db_name)
                    if rdb is not None:
                        intro[n.name] = rdb.introspect()
                if len(set(seqs.values())) > 1:
                    divergent[s] = {"seqs": seqs, "introspect": intro}
            result["divergent_shards"] = divergent
            log(f"divergent shards: {json.dumps(divergent, indent=1)}")
        total_seq = 0
        for s in range(n_shards):
            # max across replicas: acked writes live on at least the
            # leader, so a lagging follower must not register as "loss"
            # when the convergence wait timed out
            apps = [
                app for n in nodes
                if (app := n.handler.db_manager.get_db(
                    segment_to_db_name("seg", s))) is not None
            ]
            total_seq += max(
                (a.latest_sequence_number() for a in apps), default=0)
        result.update({
            "writes_acked": written[0],
            "write_errors": errors[0],
            "victim_led_shards": led,
            "reelected_all": bool(reelected),
            "reelect_sec": round(reelect_sec, 2),
            "replicas_converged": bool(conv),
            "total_seq_after": total_seq,
            "acked_loss_frac": round(
                max(0, written[0] - total_seq) / max(1, written[0]), 4),
        })
        log(f"failover: {json.dumps(result)}")
        return result
    finally:
        stop.set()
        for t in threads:
            t.join(5)
        for n in nodes:
            try:
                n.stop(graceful=True)
            except Exception:
                pass
        try:
            ctrl.stop()
        except Exception:
            pass
        try:
            coord.stop()
        except Exception:
            pass
        shutil.rmtree(tmp, ignore_errors=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--shards", type=int, default=256)
    ap.add_argument("--storm_sec", type=float, default=60)
    ap.add_argument("--writers", type=int, default=8)
    ap.add_argument("--readers", type=int, default=4)
    ap.add_argument("--value_bytes", type=int, default=256)
    ap.add_argument("--cluster_shards", type=int, default=32)
    ap.add_argument("--cluster_sec", type=float, default=20)
    ap.add_argument("--skip_cluster", action="store_true")
    ap.add_argument("--out", default="benchmarks/results/soak.json")
    args = ap.parse_args()

    result = {
        "bench": "soak",
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "storm": storm_phase(args.shards, args.storm_sec, args.writers,
                             args.readers, args.value_bytes),
    }
    if not args.skip_cluster:
        result["failover"] = failover_phase(args.cluster_shards,
                                            args.cluster_sec)
    # samples == 0 means the stall path never ran (writes spread over
    # many shards may never fill any one imm queue) — that's
    # indeterminate, NOT a met target; bench.py's dedicated storm is the
    # authoritative p99 measurement.
    if result["storm"].get("write_stall_samples", 0) > 0:
        result["write_stall_target_met"] = bool(
            result["storm"]["write_stall_p99_ms"] < 10.0)
    else:
        result["write_stall_target_met"] = None
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))
    # Non-convergence after failover is a correctness failure, not a perf
    # footnote: the run must FAIL so regressions can't hide in the JSON.
    fo = result.get("failover", {})
    if fo and not fo.get("replicas_converged", True):
        sys.exit(1)
    if fo.get("error"):
        sys.exit(1)


if __name__ == "__main__":
    main()
