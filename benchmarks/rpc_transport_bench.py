#!/usr/bin/env python
"""RPC byte-layer microbench: echo throughput per transport.

The 3-replica bench measures the whole serving path, where (after the
round-6 pipelining) follower apply + WAL fsync dominate and the byte
layer is a minority cost. THIS bench isolates the layer this round made
pluggable: one in-process echo server, K concurrent callers issuing
small calls as fast as they resolve, interleaved A/B across
tcp/uds/loopback (benchmarks/ab_runner.py). It also reports the uds
transport's coalescing counters — frames per sendmsg/recv syscall — the
mechanism behind the win, not just its effect.

    python -m benchmarks.rpc_transport_bench --calls 3000 --concurrency 64

Emits JSON with calls_per_sec per transport, ratios vs tcp, and
frames_per_sendmsg / frames_per_recv for the vectored path.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.ab_runner import host_calibration, run_interleaved  # noqa: E402

TRANSPORTS = ("tcp", "uds", "loopback")


def log(msg):
    print(msg, file=sys.stderr, flush=True)


class _EchoHandler:
    async def handle_echo(self, payload: str = "", blob: bytes = b""):
        return {"payload": payload, "n": len(blob)}


async def _drive(port: int, calls: int, concurrency: int,
                 value_bytes: int) -> dict:
    from rocksplicator_tpu.rpc.client import RpcClient

    client = RpcClient("127.0.0.1", port)
    await client.connect()
    blob = b"x" * value_bytes
    sem = asyncio.Semaphore(concurrency)
    done = 0

    async def one(i: int):
        nonlocal done
        async with sem:
            r = await client.call("echo", {"payload": f"c{i}", "blob": blob})
            assert r["n"] == value_bytes
            done += 1

    t0 = time.perf_counter()
    await asyncio.gather(*(one(i) for i in range(calls)))
    elapsed = time.perf_counter() - t0
    conn = client._conn
    coalesce = {}
    if hasattr(conn, "sendmsg_calls") and conn.sendmsg_calls:
        coalesce = {
            "frames_sent": conn.frames_sent,
            "sendmsg_calls": conn.sendmsg_calls,
            "frames_per_sendmsg": round(
                conn.frames_sent / conn.sendmsg_calls, 1),
            "frames_received": conn.frames_received,
            "recv_calls": conn.recv_calls,
            "frames_per_recv": round(
                conn.frames_received / max(1, conn.recv_calls), 1),
        }
    scheme = client.transport_scheme
    await client.close()
    return {
        "transport": scheme,
        "calls": done,
        "calls_per_sec": round(done / elapsed, 1),
        **coalesce,
    }


def run_one(transport: str, calls: int, concurrency: int,
            value_bytes: int) -> dict:
    """One echo run: server + client in this process under the policy.
    A fresh event loop per run keeps loopback registry/loop pairing
    clean across interleaved reps."""
    os.environ["RSTPU_TRANSPORT"] = transport

    async def serve_and_drive():
        from rocksplicator_tpu.rpc.ioloop import IoLoop
        from rocksplicator_tpu.rpc.server import RpcServer

        # the server's IoLoop is THIS loop: run its async start directly
        srv = RpcServer(port=0, host="127.0.0.1")
        srv.add_handler(_EchoHandler())
        await srv._start_async()
        try:
            res = await _drive(srv.port, calls, concurrency, value_bytes)
        finally:
            await srv._stop_async()
        return res

    return asyncio.run(serve_and_drive())


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--calls", type=int, default=3000)
    ap.add_argument("--concurrency", type=int, default=64)
    ap.add_argument("--value_bytes", type=int, default=1024)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--transports", default="tcp,uds,loopback")
    ap.add_argument("--out",
                    default="benchmarks/results/rpc_transport_bench.json")
    args = ap.parse_args()

    names = [t.strip() for t in args.transports.split(",") if t.strip()]
    for t in names:
        if t not in TRANSPORTS:
            ap.error(f"unknown transport {t!r}")
    saved = os.environ.get("RSTPU_TRANSPORT")
    try:
        ab = run_interleaved(
            [(t, (lambda t=t: run_one(
                t, args.calls, args.concurrency, args.value_bytes)))
             for t in names],
            reps=args.reps, key="calls_per_sec", log=log)
    finally:
        if saved is None:
            os.environ.pop("RSTPU_TRANSPORT", None)
        else:
            os.environ["RSTPU_TRANSPORT"] = saved
    with tempfile.TemporaryDirectory() as td:
        calib = host_calibration(td)
    result = {
        "bench": "rpc_transport_echo",
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "config": {
            "calls": args.calls, "concurrency": args.concurrency,
            "value_bytes": args.value_bytes, "transports": names,
            "topology": "echo server + client, one process, one loop",
        },
        "ab": ab,
        "host_calibration": calib,
    }
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps({
        "calls_per_sec_median": {
            n: s.get("median") for n, s in ab.get("summary", {}).items()},
        **{k: v for k, v in ab.items() if k.startswith("ratio_vs_")},
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
