"""Component-level device profiling for the compaction pipeline.

Times pipeline stages and rewrite candidates in isolation on the live
device to locate the wall-clock. Probe sets:

  components — sorts, gathers, scans, bloom, encode, full model
  variants   — rewrite candidates (payload-through-sort, seg-scan bloom,
               encode layouts, scatter)

Measurement note (axon tunnel): ``jax.block_until_ready`` does NOT block
on the tunneled platform — launches queue and "complete" instantly. Only
a device-to-host readback drains the queue (and flips the session into
synchronous dispatch). Every timing here forces a readback, and the first
readback happens before t0, so numbers are true per-iteration wall-clock
*including* the per-dispatch floor (~23 ms measured; see the ``floor``
probe).

Usage:  python -m benchmarks.profile_device [--set components|variants|all]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def _readback(out):
    """Force a real host sync (see module docstring)."""
    leaves = jax.tree_util.tree_leaves(out)
    np.asarray(leaves[0]).ravel()[:1]


def timeit(fn, args, iters=3, name="?"):
    out = fn(*args)
    _readback(out)
    t0 = time.monotonic()
    for _ in range(iters):
        out = fn(*args)
    _readback(out)
    dt = (time.monotonic() - t0) / iters
    log(f"{name:<46s} {dt * 1e3:9.2f} ms/iter")
    return dt


def build_inputs(n: int, s: int):
    import jax.numpy as jnp

    from rocksplicator_tpu.models.compaction_model import synth_counter_batch

    shards = [
        synth_counter_batch(n, key_space=n // 8, seed=1234 + i, key_bytes=16)
        for i in range(s)
    ]
    st = {k: jnp.asarray(np.stack([b[k] for b in shards])) for k in shards[0]}
    _readback(st["seq_lo"])  # flip the tunnel session into sync dispatch
    return st


def probe_components(st, n, iters, results):
    import jax.numpy as jnp
    from jax import lax

    from rocksplicator_tpu.models import CompactionModel
    from rocksplicator_tpu.ops.bloom_tpu import bloom_build_tpu
    from rocksplicator_tpu.ops.compaction_kernel import (
        _sort_merge_order, merge_resolve_kernel)

    small = jnp.arange(1024, dtype=jnp.uint32)
    results["floor"] = timeit(
        jax.jit(lambda x: x + 1), (small,), iters, "floor (tiny launch)")

    u32 = st["seq_lo"]

    def sort2(x):
        iota = lax.iota(jnp.uint32, x.shape[0])
        return lax.sort((x, iota), num_keys=1, is_stable=False)

    results["sort_2op"] = timeit(
        jax.jit(jax.vmap(sort2)), (u32,), iters, "sort 2-op u32 (argsort)")

    def sort_fast(kwb, klen, shi, slo, valid):
        return _sort_merge_order(kwb, klen, shi, slo, valid, (),
                                 uniform_klen=True, seq32=True,
                                 key_words=4)[3]

    results["sort_6key"] = timeit(
        jax.jit(jax.vmap(sort_fast)),
        (st["key_words_be"], st["key_len"], st["seq_hi"], st["seq_lo"],
         st["valid"]),
        iters, "sort 6-key fast path (no payload)")

    idx = jnp.argsort(st["seq_lo"], axis=-1).astype(jnp.uint32)
    _readback(idx)

    def take1d(c, idx):
        return jnp.take_along_axis(c, idx, axis=-1)

    results["take_1d"] = timeit(
        jax.jit(take1d), (u32, idx), iters, "take 1-D (the gather cost)")

    def scans(x):
        iota = lax.iota(jnp.int32, x.shape[0])
        return jnp.cumsum(x) + lax.cummax(jnp.where(x > 0, iota, 0))

    results["scans"] = timeit(
        jax.jit(jax.vmap(scans)), (st["seq_lo"].astype(jnp.int32),),
        iters, "cumsum+cummax")

    model = CompactionModel(capacity=n, uniform_klen=True, seq32=True,
                            key_words=4)
    margs = (st["key_words_be"], st["key_len"],
             st["seq_hi"], st["seq_lo"], st["vtype"], st["val_words"],
             st["val_len"], st["valid"])

    def mrk(*a):
        return merge_resolve_kernel(
            *a, uniform_klen=True, seq32=True, key_words=4)

    results["merge_resolve"] = timeit(
        jax.jit(jax.vmap(mrk)), margs, iters, "merge_resolve_kernel")

    results["bloom"] = timeit(
        jax.jit(jax.vmap(lambda kwl, kl, v: bloom_build_tpu(
            kwl, kl, v, num_words=model.num_bloom_words))),
        (st["key_words_le"], st["key_len"], st["valid"]),
        iters, "bloom_build_tpu")

    results["full_model"] = timeit(
        jax.jit(jax.vmap(model.forward)), margs, iters, "FULL model.forward")


def probe_variants(st, n, iters, results):
    import jax.numpy as jnp
    from jax import lax

    kw = st["key_words_be"]

    def sort10(kw, slo, vt, vw, vl, valid):
        inval = jnp.where(valid, jnp.uint32(0), jnp.uint32(1))
        ops = (inval, kw[:, 0], kw[:, 1], kw[:, 2], kw[:, 3], ~slo,
               vt, vw[:, 0], vw[:, 1], vl)
        return lax.sort(ops, num_keys=6, is_stable=False)

    results["sort_10op_payload"] = timeit(
        jax.jit(jax.vmap(sort10)),
        (kw, st["seq_lo"], st["vtype"], st["val_words"], st["val_len"],
         st["valid"]),
        iters, "sort 10-op (payload-through)")

    # minor-dim materialization: why rows must stay planar
    def stack_rows(slo, shi, vt, vw):
        m = slo.shape[0]
        lanes = [jnp.full((m,), jnp.uint32(16)), slo, shi, vt,
                 vw[:, 0], vw[:, 1]]
        return jnp.stack(lanes, axis=1)

    results["stack_minor6"] = timeit(
        jax.jit(jax.vmap(stack_rows)),
        (st["seq_lo"], st["seq_hi"], st["vtype"], st["val_words"]),
        iters, "stack 6 lanes -> (n, 6) minor-dim")

    def scatter_only(sidx, val):
        out = jnp.zeros(n + 1, dtype=jnp.uint32)
        return out.at[sidx].set(val, mode="drop")[:n]

    sidx = jnp.argsort(st["seq_lo"], axis=-1).astype(jnp.int32)
    _readback(sidx)
    results["scatter_set"] = timeit(
        jax.jit(jax.vmap(scatter_only)), (sidx, st["seq_lo"]),
        iters, "scatter .at[].set one lane")


def probe_mergenet(st, n, iters, results):
    """Full-sort kernel vs the sorted-runs bitonic merge network at the
    bench shape. Run pre-sorting happens OUTSIDE the timed region — real
    compaction inputs (SSTs, memtable dumps) arrive sorted."""
    import jax.numpy as jnp

    from rocksplicator_tpu.ops.compaction_kernel import (
        _sort_merge_order, merge_resolve_kernel)
    from rocksplicator_tpu.ops.merge_network import (
        merge_resolve_runs_kernel, merge_sorted_lanes)

    margs = (st["key_words_be"], st["key_len"], st["seq_hi"], st["seq_lo"],
             st["vtype"], st["val_words"], st["val_len"], st["valid"])

    def mrk(*a):
        return merge_resolve_kernel(
            *a, uniform_klen=True, seq32=True, key_words=4)

    results["kernel_fullsort"] = timeit(
        jax.jit(jax.vmap(mrk)), margs, iters,
        "merge_resolve_kernel (full sort)")

    def presort_runs(runs):
        """(S, n) shard lanes -> (S, R, L) per-run-sorted lanes."""
        L = n // runs

        def sort_one(kwb, klen, shi, slo, vt, vw, vl, valid):
            key_lanes, _, _, slo_s, valid_s, payload = _sort_merge_order(
                kwb, klen, shi, slo, valid,
                (vt, vw[:, 0], vw[:, 1], vl),
                uniform_klen=True, seq32=True, key_words=4)
            kw6 = jnp.stack(
                list(key_lanes) + [jnp.zeros_like(slo_s)] * 2, axis=1)
            # klen/shi come back None from the fast-path sort; rebuild
            # them as the constants the promises assert so every lane in
            # the dict is aligned with the sorted row order
            return {
                "key_words_be": kw6,
                "key_len": jnp.full_like(klen, 16),
                "seq_hi": jnp.zeros_like(shi),
                "seq_lo": slo_s,
                "vtype": payload[0],
                "val_words": jnp.stack(payload[1:3], axis=1),
                "val_len": payload[3],
                "valid": valid_s,
            }

        def shard_to_runs(kwb, klen, shi, slo, vt, vw, vl, valid):
            rs = (kwb.reshape(runs, L, 6), klen.reshape(runs, L),
                  shi.reshape(runs, L), slo.reshape(runs, L),
                  vt.reshape(runs, L), vw.reshape(runs, L, 2),
                  vl.reshape(runs, L), valid.reshape(runs, L))
            return jax.vmap(sort_one)(*rs)

        out = jax.jit(jax.vmap(shard_to_runs))(*margs)
        _readback(out)
        return out

    for runs in (8, 32):
        rst = presort_runs(runs)
        rargs = (rst["key_words_be"], rst["key_len"], rst["seq_hi"],
                 rst["seq_lo"], rst["vtype"], rst["val_words"],
                 rst["val_len"], rst["valid"])

        def tree_only(kwb, slo, valid):
            inval = jnp.where(valid, jnp.uint32(0), jnp.uint32(1))
            lanes = [inval] + [kwb[:, :, w] for w in range(4)] + [~slo]
            return merge_sorted_lanes(lanes, 6)

        results[f"mergenet_tree_only_r{runs}"] = timeit(
            jax.jit(jax.vmap(tree_only)),
            (rst["key_words_be"], rst["seq_lo"], rst["valid"]),
            iters, f"merge tree only ({runs} runs, no payload)")

        def mrrk(*a):
            return merge_resolve_runs_kernel(
                *a, uniform_klen=True, seq32=True, key_words=4)

        results[f"kernel_mergenet_r{runs}"] = timeit(
            jax.jit(jax.vmap(mrrk)), rargs, iters,
            f"merge_resolve_RUNS_kernel ({runs} runs)")


def probe_pallas_sort(st, n, iters, results):
    """lax.sort vs the VMEM-resident Pallas bitonic sort, standalone and
    inside the full merge-resolve kernel (PERF.md round-2 lever: the
    sort's HBM traffic is the dominant device cost)."""
    from rocksplicator_tpu.ops.compaction_kernel import (
        composite_key_lanes, merge_resolve_kernel)
    from rocksplicator_tpu.ops.pallas_sort import sort_lanes

    def lanes_of(kwb, klen, shi, slo, vt, vw, vl, valid):
        inval = jnp.where(valid, jnp.uint32(0), jnp.uint32(1))
        keys = composite_key_lanes(
            inval, (kwb[:, w] for w in range(4)), klen, shi, slo,
            uniform_klen=True, seq32=True)
        payload = [vt, vl] + [vw[:, w] for w in range(vw.shape[1])]
        return keys, payload

    margs = (st["key_words_be"], st["key_len"], st["seq_hi"],
             st["seq_lo"], st["vtype"], st["val_words"], st["val_len"],
             st["valid"])

    for backend in ("lax", "pallas"):
        def sort_only(*a, _b=backend):
            keys, payload = lanes_of(*a)
            return sort_lanes(tuple(keys + payload), num_keys=len(keys),
                              backend=_b)

        results[f"sort_only_{backend}"] = timeit(
            jax.jit(jax.vmap(sort_only)), margs, iters,
            f"10-operand sort, {backend} backend")

    for backend in ("lax", "pallas", "pallas_fused"):
        def full(*a, _b=backend):
            return merge_resolve_kernel(
                *a, uniform_klen=True, seq32=True, key_words=4,
                sort_backend=_b)

        results[f"kernel_{backend}_sort"] = timeit(
            jax.jit(jax.vmap(full)), margs, iters,
            f"merge_resolve_kernel, {backend} sort")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--entries", type=int, default=1 << 17)
    ap.add_argument("--shards", type=int, default=8)
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--set", default="components",
                    choices=("components", "variants", "mergenet",
                             "pallas", "all"))
    args = ap.parse_args()

    log(f"platform={jax.default_backend()} shards={args.shards} "
        f"entries={args.entries}")
    if os.environ.get("RSTPU_REQUIRE_ACCEL") and \
            jax.default_backend() == "cpu":
        # prober seam: a CPU fallback is useless here (interpret-mode
        # pallas takes minutes per trace) — fail fast so the caller
        # retries later instead of wedging on emulation
        log("RSTPU_REQUIRE_ACCEL set but backend is cpu — aborting")
        sys.exit(3)
    st = build_inputs(args.entries, args.shards)
    results = {}
    if args.set in ("components", "all"):
        probe_components(st, args.entries, args.iters, results)
    if args.set in ("variants", "all"):
        probe_variants(st, args.entries, args.iters, results)
    if args.set in ("mergenet", "all"):
        probe_mergenet(st, args.entries, args.iters, results)
    if args.set in ("pallas", "all"):
        probe_pallas_sort(st, args.entries, args.iters, results)
    print(json.dumps({k: round(v * 1e3, 2) for k, v in results.items()}))


if __name__ == "__main__":
    main()
