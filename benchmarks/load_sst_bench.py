#!/usr/bin/env python
"""BASELINE config #2/#3-shaped benchmark: multi-shard load_sst end-to-end.

Drives the FULL north-star path on real DBs through the admin RPC surface:
build per-shard SST sets → upload to the object store → addS3SstFilesToDB
on every shard (parallel download, ingest, post-load compaction through the
configured CompactionBackend) — measuring wall-clock and GB/s for the CPU
backend vs the TPU backend.

    python -m benchmarks.load_sst_bench --shards 64 --keys_per_shard 20000
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import struct
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from rocksplicator_tpu.admin import AdminHandler
from rocksplicator_tpu.replication import Replicator
from rocksplicator_tpu.rpc import IoLoop, RpcClientPool, RpcServer
from rocksplicator_tpu.storage import DBOptions, OpType, UInt64AddOperator, WriteBatch
from rocksplicator_tpu.storage.sst import SSTWriter
from rocksplicator_tpu.utils.objectstore import LocalObjectStore
from rocksplicator_tpu.utils.segment_utils import segment_to_db_name
from rocksplicator_tpu.utils.stats import Stats

pack64 = struct.Struct("<q").pack


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def build_sst_sets(store, shards, keys_per_shard, tmp, key_bytes=16):
    """Per-shard sorted SST files uploaded under sst/<shard:05d>/."""
    total_bytes = 0
    for shard in range(shards):
        path = os.path.join(tmp, f"bulk{shard:05d}.tsst")
        w = SSTWriter(path)
        for i in range(keys_per_shard):
            key = f"s{shard:03d}-key{i:08d}".encode()[:key_bytes]
            w.add(key, 0, OpType.PUT, pack64(i))
        w.finish()
        total_bytes += os.path.getsize(path)
        store.put_object(path, f"sst/{shard:05d}/bulk.tsst")
        os.remove(path)
    return total_bytes


def run_load(handler_kwargs, store_uri, shards, keys_per_shard,
             write_frac, label, rocksdb_dir):
    replicator = Replicator(port=0)
    handler = AdminHandler(rocksdb_dir, replicator, **handler_kwargs)
    server = RpcServer(port=0, ioloop=replicator.ioloop)
    server.add_handler(handler)
    server.start()
    ioloop = IoLoop.default()
    pool = RpcClientPool()

    def call(method, **args):
        async def go():
            return await pool.call("127.0.0.1", server.port, method, args,
                                   timeout=600)

        return ioloop.run_sync(go(), timeout=610)

    try:
        for shard in range(shards):
            call("add_db", db_name=segment_to_db_name("seg", shard),
                 role="LEADER")
        # pre-load writes so the post-load compaction has overlap work
        n_writes = int(keys_per_shard * write_frac)
        for shard in range(shards):
            app_db = handler.db_manager.get_db(segment_to_db_name("seg", shard))
            for i in range(0, n_writes):
                app_db.write(WriteBatch().put(
                    f"s{shard:03d}-key{i * 7:08d}".encode()[:16], pack64(-1)))
        t0 = time.monotonic()
        for shard in range(shards):
            call("add_s3_sst_files_to_db",
                 db_name=segment_to_db_name("seg", shard),
                 s3_bucket=store_uri, s3_path=f"sst/{shard:05d}",
                 compact_db_after_load=True)
        elapsed = time.monotonic() - t0
        # correctness spot-checks
        for shard in range(0, shards, max(1, shards // 8)):
            app_db = handler.db_manager.get_db(segment_to_db_name("seg", shard))
            assert app_db.get(
                f"s{shard:03d}-key{(keys_per_shard - 1):08d}".encode()[:16]
            ) == pack64(keys_per_shard - 1)
        return elapsed
    finally:
        server.stop()
        handler.close()
        replicator.stop()
        ioloop.run_sync(pool.close())


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--shards", type=int, default=16)
    p.add_argument("--keys_per_shard", type=int, default=20000)
    p.add_argument("--write_frac", type=float, default=0.2)
    args = p.parse_args(argv)

    tmp = tempfile.mkdtemp(prefix="loadsst-bench-")
    store_uri = os.path.join(tmp, "bucket")
    store = LocalObjectStore(store_uri)
    total_bytes = build_sst_sets(store, args.shards, args.keys_per_shard, tmp)
    log(f"built {args.shards} shard SST sets, {total_bytes / 1e6:.1f} MB")

    results = {}
    for label, kwargs in (
        ("cpu", {}),
        ("tpu", {"tpu_compaction": True}),
    ):
        elapsed = run_load(
            kwargs, store_uri, args.shards, args.keys_per_shard,
            args.write_frac, label, os.path.join(tmp, f"dbs-{label}"),
        )
        gbps = total_bytes / elapsed / 1e9
        results[label] = gbps
        log(f"{label}: load_sst of {args.shards} shards in {elapsed:.2f}s "
            f"= {gbps:.3f} GB/s")

    out = {
        "metric": "load_sst_end_to_end",
        "value": round(results["tpu"], 4),
        "unit": "GB/s",
        "vs_baseline": round(results["tpu"] / results["cpu"], 2)
        if results["cpu"] else 0.0,
        "shards": args.shards,
        "keys_per_shard": args.keys_per_shard,
        "total_mb": round(total_bytes / 1e6, 1),
        "cpu_gbps": round(results["cpu"], 4),
    }
    print(json.dumps(out), flush=True)
    shutil.rmtree(tmp, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
