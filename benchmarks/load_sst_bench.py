#!/usr/bin/env python
"""BASELINE config #2/#3-shaped benchmark: multi-shard load_sst end-to-end.

Drives the FULL north-star path on real DBs through the admin RPC surface:
build per-shard SST sets → upload to the object store → addS3SstFilesToDB
on every shard — measuring wall-clock and GB/s for the CPU backend vs the
TPU backend.

Round-7 pipelining (ISSUE 3): shard ingest RPCs are issued CONCURRENTLY on
the ioloop through a bounded window (AckWindow-style flow control,
``--window``, default 8 in flight) instead of strictly serially; the
handler narrows its per-db admin lock so shard k+1's download overlaps
shard k's engine ingest, and post-load compactions coalesce cross-shard in
the BatchCompactor. ``--trace`` emits the slowest-shard ingest span tree
and per-phase totals (download/validate/ingest/meta/compact) from the
in-process SpanCollector.

    python -m benchmarks.load_sst_bench --shards 16 --keys_per_shard 20000
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import shutil
import struct
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# persistent XLA compile cache (tests/conftest.py does the same): the TPU
# config's kernel compiles are identical run to run — warm runs measure
# the pipeline, not the compiler
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/rstpu_test_xla_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1.0")

from rocksplicator_tpu.admin import AdminHandler
# Warm the engine's lazily-imported kernel deps (ops → jax, ~1.5 s) before
# any timed region: a serving node has them loaded; without this the first
# shard's flush pays the import inside its ingest span and every
# concurrently-admitted shard blocks on the same import lock.
import rocksplicator_tpu.ops  # noqa: F401

try:  # jax < 0.5 ignores the cache env vars; set the config directly
    import jax

    jax.config.update("jax_compilation_cache_dir",
                      os.environ["JAX_COMPILATION_CACHE_DIR"])
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
except Exception:
    pass
from rocksplicator_tpu.observability.collector import SpanCollector, render_trace
from rocksplicator_tpu.replication import Replicator
from rocksplicator_tpu.rpc import IoLoop, RpcClientPool, RpcServer
from rocksplicator_tpu.storage import OpType, WriteBatch
from rocksplicator_tpu.storage.sst import SSTWriter
from rocksplicator_tpu.utils.objectstore import LocalObjectStore
from rocksplicator_tpu.utils.segment_utils import segment_to_db_name

pack64 = struct.Struct("<q").pack


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def build_sst_sets(store, shards, keys_per_shard, tmp, key_bytes=16):
    """Per-shard sorted SST files uploaded under sst/<shard:05d>/."""
    total_bytes = 0
    for shard in range(shards):
        path = os.path.join(tmp, f"bulk{shard:05d}.tsst")
        w = SSTWriter(path)
        for i in range(keys_per_shard):
            key = f"s{shard:03d}-key{i:08d}".encode()[:key_bytes]
            w.add(key, 0, OpType.PUT, pack64(i))
        w.finish()
        total_bytes += os.path.getsize(path)
        store.put_object(path, f"sst/{shard:05d}/bulk.tsst")
        os.remove(path)
    return total_bytes


def run_load(handler_kwargs, store_uri, shards, keys_per_shard,
             write_frac, label, rocksdb_dir, window):
    """One labeled pass. Returns a per-run result dict (elapsed, spot-check
    failures, per-phase span totals, slowest-shard trace)."""
    # fresh span ring per pass so cpu/tpu attributions don't mix
    SpanCollector.reset_for_test()
    replicator = Replicator(port=0)
    handler = AdminHandler(
        rocksdb_dir, replicator,
        executor_threads=window + 4,
        # the client honors the same window, so the admission gate never
        # rejects in-bench; real orchestrators retry on TOO_MANY_REQUESTS
        max_sst_loading_concurrency=window,
        **handler_kwargs)
    server = RpcServer(port=0, ioloop=replicator.ioloop)
    server.add_handler(handler)
    server.start()
    ioloop = IoLoop.default()
    pool = RpcClientPool()

    def call(method, **args):
        async def go():
            return await pool.call("127.0.0.1", server.port, method, args,
                                   timeout=600)

        return ioloop.run_sync(go(), timeout=610)

    try:
        for shard in range(shards):
            call("add_db", db_name=segment_to_db_name("seg", shard),
                 role="LEADER")
        # pre-load writes so the post-load compaction has overlap work
        n_writes = int(keys_per_shard * write_frac)
        for shard in range(shards):
            app_db = handler.db_manager.get_db(segment_to_db_name("seg", shard))
            for i in range(0, n_writes):
                app_db.write(WriteBatch().put(
                    f"s{shard:03d}-key{i * 7:08d}".encode()[:16], pack64(-1)))

        async def fan_out():
            # bounded concurrent shard fan-out — the serial per-shard
            # run_sync loop was the single largest orchestration cost
            sem = asyncio.Semaphore(window)

            async def one(shard):
                async with sem:
                    return await pool.call(
                        "127.0.0.1", server.port, "add_s3_sst_files_to_db",
                        {"db_name": segment_to_db_name("seg", shard),
                         "s3_bucket": store_uri,
                         "s3_path": f"sst/{shard:05d}",
                         "compact_db_after_load": True},
                        timeout=600)

            return await asyncio.gather(*(one(s) for s in range(shards)))

        t0 = time.monotonic()
        # the overall cap must scale with the shard count (each RPC keeps
        # its own 600s budget; a serial --window 1 A/B on a slow host can
        # legitimately exceed a flat 610s total)
        ioloop.run_sync(fan_out(), timeout=610 + 30 * shards)
        elapsed = time.monotonic() - t0

        # correctness spot-checks: every shard
        failures = 0
        for shard in range(shards):
            app_db = handler.db_manager.get_db(segment_to_db_name("seg", shard))
            want = pack64(keys_per_shard - 1)
            if app_db.get(
                f"s{shard:03d}-key{(keys_per_shard - 1):08d}".encode()[:16]
            ) != want:
                failures += 1
                log(f"{label}: SPOT-CHECK FAILURE shard {shard}")
        collector = SpanCollector.get()
        phases = collector.phase_totals("admin.")
        slowest = collector.slowest_trace("admin.add_s3_sst")
        trace_lines = None
        if slowest is not None:
            trace_lines = render_trace(
                slowest["trace"]["spans"], slowest["trace"]["start_ms"])
        return {
            "elapsed_s": round(elapsed, 3),
            "spot_check_failures": failures,
            "window": window,
            "phase_ms": phases,
            "compact_batch_sizes": list(handler._batch_compactor.batch_sizes),
            "slowest_shard_trace": trace_lines,
        }
    finally:
        server.stop()
        handler.close()
        replicator.stop()
        ioloop.run_sync(pool.close())


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--shards", type=int, default=16)
    p.add_argument("--keys_per_shard", type=int, default=20000)
    p.add_argument("--write_frac", type=float, default=0.2)
    p.add_argument("--window", type=int, default=8,
                   help="max in-flight shard ingest RPCs (flow-control "
                        "window)")
    p.add_argument("--configs", default="cpu,tpu",
                   help="comma-separated subset of cpu,tpu to run")
    p.add_argument("--trace", action="store_true",
                   help="include the slowest-shard ingest span tree in the "
                        "output JSON")
    p.add_argument("--out", default=None, help="also write the result JSON "
                                               "to this path")
    p.add_argument("--trace_out", default=None,
                   help="write a standalone trace-attribution artifact "
                        "(implies --trace)")
    args = p.parse_args(argv)
    if args.trace_out:
        args.trace = True

    tmp = tempfile.mkdtemp(prefix="loadsst-bench-")
    store_uri = os.path.join(tmp, "bucket")
    store = LocalObjectStore(store_uri)
    total_bytes = build_sst_sets(store, args.shards, args.keys_per_shard, tmp)
    log(f"built {args.shards} shard SST sets, {total_bytes / 1e6:.1f} MB")

    configs = {"cpu": {}, "tpu": {"tpu_compaction": True}}
    runs = {}
    results = {}
    for label in [c.strip() for c in args.configs.split(",") if c.strip()]:
        run = run_load(
            configs[label], store_uri, args.shards, args.keys_per_shard,
            args.write_frac, label, os.path.join(tmp, f"dbs-{label}"),
            args.window,
        )
        gbps = total_bytes / run["elapsed_s"] / 1e9
        run["gbps"] = round(gbps, 4)
        runs[label] = run
        results[label] = gbps
        log(f"{label}: load_sst of {args.shards} shards in "
            f"{run['elapsed_s']:.2f}s = {gbps:.4f} GB/s "
            f"(window={args.window}, "
            f"spot_check_failures={run['spot_check_failures']}, "
            f"compact_batches={run['compact_batch_sizes']})")

    headline = results.get("tpu", results.get("cpu", 0.0))
    out = {
        "metric": "load_sst_end_to_end",
        "value": round(headline, 4),
        "unit": "GB/s",
        "vs_baseline": round(results["tpu"] / results["cpu"], 2)
        if results.get("cpu") and results.get("tpu") else 0.0,
        "shards": args.shards,
        "keys_per_shard": args.keys_per_shard,
        "total_mb": round(total_bytes / 1e6, 1),
        "window": args.window,
        "cpu_gbps": round(results.get("cpu", 0.0), 4),
        "spot_check_failures": sum(
            r["spot_check_failures"] for r in runs.values()),
        "runs": {
            label: {k: v for k, v in run.items()
                    if args.trace or k != "slowest_shard_trace"}
            for label, run in runs.items()
        },
    }
    print(json.dumps(out), flush=True)
    if args.out:
        os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1)
            f.write("\n")
    if args.trace_out:
        artifact = {
            "bench": "load_sst_pipelined",
            "shards": args.shards,
            "keys_per_shard": args.keys_per_shard,
            "window": args.window,
            "total_mb": round(total_bytes / 1e6, 1),
            "attribution": {
                label: {
                    "elapsed_s": run["elapsed_s"],
                    "gbps": run["gbps"],
                    "phase_ms": run["phase_ms"],
                    "compact_batch_sizes": run["compact_batch_sizes"],
                    "slowest_shard_trace": run["slowest_shard_trace"],
                }
                for label, run in runs.items()
            },
        }
        os.makedirs(
            os.path.dirname(os.path.abspath(args.trace_out)), exist_ok=True)
        with open(args.trace_out, "w") as f:
            json.dump(artifact, f, indent=1)
            f.write("\n")
    shutil.rmtree(tmp, ignore_errors=True)
    return 0 if out["spot_check_failures"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
