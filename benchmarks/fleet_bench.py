#!/usr/bin/env python
"""Fleet-density macro-bench (round 22): an N-node / S-shard serving
fleet driven through a SCRIPTED timeline, plus the mux A/B.

The 3-process macro-bench measures one replica set; real density is a
fleet where every node is simultaneously a leader for some shards and
a follower for others. This harness spawns N ``macro_bench --serve
topo`` children hosting S shards at replication factor 3 (leader of
shard s = node ``s % N``, followers ``(s+1) % N`` and ``(s+2) % N`` —
the interleaved-ring layout, so each node follows shards from exactly
two upstream peers) and drives a scripted timeline of serving weather:

- **baseline** — steady mixed workload, the SLO reference point;
- **diurnal** — a stepped rate curve (trough → ramp → peak → settle);
- **hot_shift** — the zipfian hot set is CONCENTRATED on ~20% of the
  shards, then jumps to a different shard subset mid-phase;
- **node_kill** — SIGKILL one node mid-phase (degraded serving gates),
  then restart it and time recovery;
- **drain** — live-drain a node under load: per shard it leads, pause
  writes → wait replicas equal → promote the next replica (epoch+1) →
  repoint the third → demote the old leader to follower; zero
  acked-write loss is gated by reading every acked put back;
- **cdc_burst** — a CDC ingest burst through the broker into a subset
  of shards while serving, gated on EXACTLY-once drain;
- **cooldown** — return to baseline rate, then require full fleet
  convergence (every replica of every shard at the same seq).

Every phase records its own SLO gate verdicts AND a `/cluster_stats`
snapshot (the spectator aggregation over the live fleet). Failures
land in the artifact's ``failures`` and the exit code.

``--ab`` runs the round-22 acceptance A/B instead: interleaved
``RSTPU_PULL_MUX=1`` vs ``0`` over fresh fleets (≥8 procs / ≥64 shards
at the default shape), measuring replication-plane frames/sec and
parked long-polls per node over an IDLE window (driver traffic would
dilute the mux's frame savings), plus applied put throughput, get p99
and acked-put readback over a load window. Gates: frames/sec and
parked long-polls reduced ≥5x, equal applied throughput, p99 no
worse, zero acked-write loss.

    python -m benchmarks.fleet_bench --nodes 10 --shards 100 \
        --out benchmarks/results/fleet_bench.json
    python -m benchmarks.fleet_bench --ab \
        --out benchmarks/results/fleet_mux_ab.json

Artifacts carry the shared ``host_calibration`` block.
"""

from __future__ import annotations

import argparse
import asyncio
import contextlib
import json
import os
import subprocess
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.ab_runner import (emit_gated_artifact,  # noqa: E402
                                  host_calibration, run_interleaved)
from benchmarks.macro_bench import (SEGMENT, Cluster,  # noqa: E402
                                    _bench_env, _cdc_value,
                                    _run_open_loop, key_of, log, parse_mix,
                                    percentile, put_value, reserve_port,
                                    shard_of)

REPLICATION_FACTOR = 3


def db_name_of(shard: int) -> str:
    from rocksplicator_tpu.utils.segment_utils import segment_to_db_name

    return segment_to_db_name(SEGMENT, shard)


# ---------------------------------------------------------------------------
# fleet cluster: N topo children, interleaved-ring replica placement
# ---------------------------------------------------------------------------


class FleetCluster:
    """N ``--serve topo`` children hosting S shards at RF=3 on the
    interleaved ring (leader of s = node s % N, followers the next two
    ring nodes), plus the driver's router/pool. Duck-types the subset
    of ``macro_bench.Cluster`` the open-loop driver uses (``shards``,
    ``router``, ``ioloop``, ``pool``)."""

    def __init__(self, root: str, nodes: int, shards: int,
                 preload_keys: int, value_bytes: int, write_window: int,
                 read_info_ttl_ms: int, transport: str,
                 executor_threads: int, with_admin: bool = True,
                 extra_env: Optional[Dict[str, str]] = None):
        if nodes < REPLICATION_FACTOR:
            raise ValueError(f"fleet needs >= {REPLICATION_FACTOR} nodes")
        self.root = root
        self.nodes = nodes
        self.shards = shards
        self.preload_keys = preload_keys
        self.value_bytes = value_bytes
        self.write_window = write_window
        self.read_info_ttl_ms = read_info_ttl_ms
        self.transport = transport
        self.executor_threads = executor_threads
        self.with_admin = with_admin
        self.leader_of: Dict[int, int] = {s: s % nodes
                                          for s in range(shards)}
        self.epochs: Dict[int, int] = {s: 0 for s in range(shards)}
        self.ports = [reserve_port() for _ in range(nodes)]
        self.admin_ports = ([reserve_port() for _ in range(nodes)]
                            if with_admin else [])
        self.alive = [False] * nodes
        self.procs: List[Optional[subprocess.Popen]] = [None] * nodes
        self._env = dict(os.environ, JAX_PLATFORMS="cpu",
                         RSTPU_TRANSPORT=transport)
        self._env.update(extra_env or {})
        self._env.pop("PALLAS_AXON_POOL_IPS", None)

        # spawn the whole fleet at once: every node is leader for some
        # shards and follower for others, so there is no "leaders
        # first" order — followers whose upstream peer is not yet
        # listening ride the fast-first-connect retry tier
        for i in range(nodes):
            self.procs[i] = self._spawn(i, preload=True)
        for i in range(nodes):
            Cluster._wait_ready(self.procs[i], f"node{i}")
            self.alive[i] = True

        os.environ["RSTPU_TRANSPORT"] = transport
        from rocksplicator_tpu.rpc.client_pool import RpcClientPool
        from rocksplicator_tpu.rpc.router import RpcRouter

        self.pool = RpcClientPool()
        self.router = RpcRouter(local_az="az-n0", pool=self.pool)
        from rocksplicator_tpu.rpc.ioloop import IoLoop

        self.ioloop = IoLoop.default()
        self.update_router()

    # -- placement ---------------------------------------------------------

    def replica_nodes(self, shard: int) -> List[int]:
        return [(shard + k) % self.nodes
                for k in range(REPLICATION_FACTOR)]

    def leaders_on(self, node: int) -> List[int]:
        return [s for s, n in sorted(self.leader_of.items()) if n == node]

    def _topo_json(self, node: int) -> str:
        topo = []
        for s in range(self.shards):
            if node not in self.replica_nodes(s):
                continue
            if self.leader_of[s] == node:
                topo.append([s, "leader", 0])
            else:
                topo.append([s, "follower",
                             self.ports[self.leader_of[s]]])
        return json.dumps(topo)

    def _spawn(self, node: int, preload: bool) -> subprocess.Popen:
        cmd = [
            sys.executable, "-m", "benchmarks.macro_bench",
            "--serve", "topo", "--topo", self._topo_json(node),
            "--port", str(self.ports[node]),
            "--shards", str(self.shards),
            "--db_dir", os.path.join(self.root, f"n{node}"),
            # restarts reopen the surviving storage: re-preloading
            # would append duplicate writes past the followers' seqs
            "--preload_keys", str(self.preload_keys if preload else 0),
            "--value_bytes", str(self.value_bytes),
            "--write_window", str(self.write_window),
            "--read_info_ttl_ms", str(self.read_info_ttl_ms),
            "--executor_threads", str(self.executor_threads),
        ]
        if self.admin_ports:
            cmd += ["--admin_port", str(self.admin_ports[node])]
        return subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, env=self._env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

    # -- routing -----------------------------------------------------------

    def update_router(self) -> None:
        """Re-teach the driver's router the CURRENT leader map (what
        the shardmap-agent refresh does for real clients); called after
        every drain handoff."""
        from rocksplicator_tpu.rpc.router import ClusterLayout

        layout: Dict = {SEGMENT: {"num_shards": self.shards}}
        for i, port in enumerate(self.ports):
            entries = []
            for s in range(self.shards):
                if i not in self.replica_nodes(s):
                    continue
                mark = "M" if self.leader_of[s] == i else "S"
                entries.append(f"{s:05d}:{mark}")
            if entries:
                layout[SEGMENT][
                    f"127.0.0.1:{port}:az-n{i}:{port}"] = entries
        self.router.update_layout(
            ClusterLayout.parse(json.dumps(layout).encode()))

    # -- readiness ---------------------------------------------------------

    def wait_catchup(self, total_keys: int, timeout: float = 180.0) -> None:
        """Every follower replica of every shard must serve a max_lag=0
        read of that shard's last preloaded key before the timed
        phases start."""
        from rocksplicator_tpu.rpc.errors import RpcError

        deadline = time.monotonic() + timeout
        for s in range(self.shards):
            gid = total_keys - self.shards + s
            if gid < 0:
                continue
            for node in self.replica_nodes(s):
                if node == self.leader_of[s]:
                    continue

                async def probe(port=self.ports[node], shard=s, g=gid):
                    return await self.pool.call(
                        "127.0.0.1", port, "read",
                        {"db_name": db_name_of(shard), "op": "get",
                         "keys": [key_of(g)], "max_lag": 0},
                        timeout=5.0)

                while True:
                    try:
                        r = self.ioloop.run_sync(probe(), timeout=10)
                        if r["values"][0] is not None:
                            break
                    except RpcError:
                        pass
                    if time.monotonic() > deadline:
                        raise RuntimeError(
                            f"node {node} shard {s} never caught up "
                            f"({timeout}s)")
                    time.sleep(0.1)
        log(f"  fleet caught up ({self.shards} shards x "
            f"{REPLICATION_FACTOR - 1} followers at max_lag=0)")

    # -- admin plane -------------------------------------------------------

    def admin(self, node: int, method: str, timeout: float = 15.0,
              **args):
        async def call():
            return await self.pool.call(
                "127.0.0.1", self.admin_ports[node], method, args,
                timeout=timeout)

        return self.ioloop.run_sync(call(), timeout=timeout + 5)

    def shard_seqs(self, shard: int) -> List[int]:
        return [int(self.admin(n, "get_sequence_number",
                               db_name=db_name_of(shard))["seq_num"])
                for n in self.replica_nodes(shard)]

    def wait_converged(self, shards: Optional[List[int]] = None,
                       timeout: float = 60.0) -> float:
        """Block until every replica of every given shard reports the
        same seq (quiesced fleet only). Returns the wait in seconds."""
        t0 = time.monotonic()
        deadline = t0 + timeout
        for s in (shards if shards is not None else range(self.shards)):
            while True:
                seqs = self.shard_seqs(s)
                if len(set(seqs)) == 1:
                    break
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"shard {s} never converged: seqs={seqs}")
                time.sleep(0.1)
        return time.monotonic() - t0

    # -- fault / maintenance actuators ------------------------------------

    def kill_node(self, node: int) -> None:
        p = self.procs[node]
        p.kill()
        p.wait(timeout=10)
        self.alive[node] = False
        log(f"  node{node} SIGKILLed "
            f"(led {len(self.leaders_on(node))} shards)")

    def restart_node(self, node: int) -> None:
        self.procs[node] = self._spawn(node, preload=False)
        Cluster._wait_ready(self.procs[node], f"node{node} (restart)")
        self.alive[node] = True

    def drain_node(self, node: int,
                   pause_ms: float = 20000.0,
                   catchup_timeout: float = 30.0) -> Dict:
        """Live-drain every shard ``node`` leads, one at a time: pause
        writes on the old leader (auto-expiring, so a dead drainer
        can't wedge the shard) → wait until all three replicas report
        the same seq (mode-1 acks only guarantee ONE follower has a
        write, so promotion before full catch-up could lose acked
        writes) → promote the next ring replica at epoch+1 → repoint
        the third replica → demote the old leader to a follower of the
        new one → re-teach the router. Writes to the shard error
        between pause and the router update; the phase's error budget
        absorbs that window."""
        moved = []
        t0 = time.monotonic()
        for s in list(self.leaders_on(node)):
            db = db_name_of(s)
            replicas = self.replica_nodes(s)
            new_leader = next(r for r in replicas
                              if r != node and self.alive[r])
            third = [r for r in replicas if r not in (node, new_leader)]
            self.admin(node, "pause_db_writes", db_name=db,
                       duration_ms=pause_ms)
            deadline = time.monotonic() + catchup_timeout
            while True:
                seqs = self.shard_seqs(s)
                if len(set(seqs)) == 1:
                    break
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"drain: shard {s} replicas never leveled: "
                        f"{seqs}")
                time.sleep(0.05)
            epoch = self.epochs[s] + 1
            self.epochs[s] = epoch
            self.admin(new_leader, "change_db_role_and_upstream",
                       db_name=db, new_role="LEADER", epoch=epoch,
                       timeout=30.0)
            self.admin(node, "change_db_role_and_upstream",
                       db_name=db, new_role="FOLLOWER",
                       upstream_ip="127.0.0.1",
                       upstream_port=self.ports[new_leader],
                       epoch=epoch, timeout=30.0)
            for r in third:
                self.admin(r, "change_db_role_and_upstream",
                           db_name=db, new_role="FOLLOWER",
                           upstream_ip="127.0.0.1",
                           upstream_port=self.ports[new_leader],
                           epoch=epoch, timeout=30.0)
            self.leader_of[s] = new_leader
            self.update_router()
            moved.append({"shard": s, "from": node, "to": new_leader,
                          "epoch": epoch})
        return {"shards_moved": len(moved), "moves": moved,
                "drain_sec": round(time.monotonic() - t0, 2)}

    # -- observability -----------------------------------------------------

    def scrape_node(self, node: int) -> Dict:
        async def call():
            return await self.pool.call(
                "127.0.0.1", self.ports[node], "stats", {},
                timeout=10.0)

        return self.ioloop.run_sync(call(), timeout=15)

    def counter_sums(self, prefixes: Tuple[str, ...]) -> Dict[str, float]:
        sums: Dict[str, float] = {}
        for i in range(self.nodes):
            if not self.alive[i]:
                continue
            st = self.scrape_node(i)
            for k, v in (st.get("counters") or {}).items():
                if k.startswith(prefixes):
                    sums[k] = sums.get(k, 0.0) + v["total"]
        return sums

    def cluster_stats(self) -> Dict:
        from rocksplicator_tpu.cluster.stats_aggregator import \
            ClusterStatsAggregator

        agg = ClusterStatsAggregator(pool=self.pool, ioloop=self.ioloop)
        endpoints = [("127.0.0.1", p)
                     for i, p in enumerate(self.ports) if self.alive[i]]
        return agg.scrape_and_aggregate(endpoints)

    def stop(self) -> None:
        try:
            self.ioloop.run_sync(self.pool.close(), timeout=10)
        except Exception:
            pass
        for p in self.procs:
            if p is not None and p.poll() is None:
                p.terminate()
        for p in self.procs:
            if p is not None:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()


# ---------------------------------------------------------------------------
# per-phase SLO gates + /cluster_stats snapshots
# ---------------------------------------------------------------------------


def _err_counts(summary: Dict) -> Tuple[int, int]:
    completed = sum(op["count"] for op in summary["ops"].values())
    errors = sum(op["errors"] for op in summary["ops"].values())
    return completed, errors


def slo_gate(phase: str, summary: Dict, spec: Dict,
             baseline: Optional[Dict] = None) -> Tuple[Dict, List[str]]:
    """Evaluate one phase summary against its gate spec. Returns the
    recorded gate block and the failure strings (phase-prefixed)."""
    completed, errors = _err_counts(summary)
    # the open-loop driver awaits every dispatched op, so availability
    # is exactly 1 - error_rate (there is no silent-drop channel);
    # achieved_per_sec vs the nominal rate only measures the Poisson
    # arrival draw and is recorded in the summary, not gated
    err_rate = errors / max(1, completed + errors)
    get_p99 = (summary["ops"].get("get") or {}).get("p99_ms")
    gates = {
        "spec": spec,
        "error_rate": round(err_rate, 4),
        "availability": round(1.0 - err_rate, 4),
        "value_mismatches": summary["value_mismatches"],
        "get_p99_ms": get_p99,
    }
    fails: List[str] = []
    if summary["value_mismatches"]:
        fails.append(f"{phase}: {summary['value_mismatches']} value "
                     "mismatches")
    if err_rate > spec["max_error_rate"]:
        fails.append(f"{phase}: error rate {err_rate:.3f} > "
                     f"{spec['max_error_rate']}")
    factor = spec.get("p99_factor")
    if factor and baseline is not None:
        base_p99 = (baseline["ops"].get("get") or {}).get("p99_ms")
        if base_p99 is not None and get_p99 is not None:
            bound = base_p99 * factor + spec.get("p99_slack_ms", 2.0)
            gates["get_p99_bound_ms"] = round(bound, 3)
            if get_p99 > bound:
                fails.append(
                    f"{phase}: get p99 {get_p99}ms > {bound:.1f}ms "
                    f"({factor}x baseline {base_p99}ms)")
    return gates, fails


def snapshot(cluster: FleetCluster) -> Dict:
    """A compact `/cluster_stats` snapshot for the per-phase record:
    the fleet latency merge + fleet scalars, not the full per-shard
    map (the final full snapshot is recorded once, at the end)."""
    cs = cluster.cluster_stats()
    shards = cs.get("per_shard") or {}
    counters = cs.get("counters_total") or {}
    keep = ("replicator.mux_", "replicator.pull_requests",
            "replicator.parked", "rpc.frames_", "router.")
    return {
        "endpoints": sum(1 for a in cluster.alive if a),
        "shards_reporting": len(shards),
        "max_replication_lag": cs.get("max_replication_lag"),
        "fleet_latency_ms": cs.get("fleet_latency_ms"),
        "counters": {k: v for k, v in sorted(counters.items())
                     if k.startswith(keep)},
        "scrape_errors_total": cs.get("scrape_errors_total"),
    }


def run_fleet_phase(cluster: FleetCluster, policy, rate: float,
                    duration: float, total_keys: int, value_bytes: int,
                    mix: Dict[str, float], seed: int, max_inflight: int,
                    gid_source=None,
                    acked: Optional[set] = None) -> Dict:
    res = cluster.ioloop.run_sync(
        _run_open_loop(cluster, policy, rate, duration, total_keys,
                       value_bytes, mix, seed, max_inflight,
                       gid_source=gid_source, acked_puts=acked),
        timeout=duration + 240)
    return res.summarize(rate, duration)


def readback_acked(cluster: FleetCluster, acked: set, value_bytes: int,
                   sample_cap: int = 1500) -> Dict:
    """Read a sample of acked put gids back at the CURRENT leaders with
    max_lag=0: any miss or wrong value is an acked-write loss."""
    from rocksplicator_tpu.rpc.router import ReadPolicy

    gids = sorted(acked)
    if len(gids) > sample_cap:
        step = len(gids) / sample_cap
        gids = [gids[int(i * step)] for i in range(sample_cap)]
    lost: List[int] = []

    async def check(gid: int):
        r = await cluster.router.read(
            SEGMENT, shard_of(gid, cluster.shards), op="get",
            policy=ReadPolicy.leader_only(),
            keys=[key_of(gid)], timeout=15.0)
        got = r["values"][0]
        got = bytes(got) if got is not None else None
        if got != put_value(gid, value_bytes):
            lost.append(gid)

    async def run_all():
        sem = asyncio.Semaphore(64)

        async def one(g):
            async with sem:
                await check(g)

        await asyncio.gather(*[one(g) for g in gids])

    cluster.ioloop.run_sync(run_all(), timeout=120)
    return {"acked_total": len(acked), "sampled": len(gids),
            "lost": len(lost), "lost_gids": lost[:20]}


# ---------------------------------------------------------------------------
# scripted timeline phases
# ---------------------------------------------------------------------------


def phase_baseline(cluster, args, policy, total_keys, mix, acked) -> Dict:
    log(f"phase baseline: {args.rate}/s x {args.duration}s")
    summary = run_fleet_phase(
        cluster, policy, args.rate, args.duration, total_keys,
        args.value_bytes, mix, args.seed, args.max_inflight, acked=acked)
    spec = {"max_error_rate": 0.01}
    gates, fails = slo_gate("baseline", summary, spec)
    return {"phase": "baseline", "summary": summary, "slo": gates,
            "failures": fails}


def phase_diurnal(cluster, args, policy, total_keys, mix, acked,
                  baseline) -> Dict:
    """Stepped diurnal rate curve: trough → ramp → peak (2x, open-loop
    overload by design) → settle. The p99 gate bites on the SETTLE
    step — the fleet must come back down once the peak passes."""
    steps = [("trough", 0.5), ("ramp", 1.25), ("peak", 2.0),
             ("settle", 1.0)]
    step_dur = max(1.0, args.duration / len(steps))
    curve = []
    fails: List[str] = []
    for k, (name, factor) in enumerate(steps):
        rate = args.rate * factor
        log(f"phase diurnal/{name}: {rate:.0f}/s x {step_dur:.1f}s")
        s = run_fleet_phase(
            cluster, policy, rate, step_dur, total_keys,
            args.value_bytes, mix, args.seed + 100 + k,
            args.max_inflight, acked=acked)
        spec = {"max_error_rate": 0.05}
        if name == "settle":
            spec.update({"max_error_rate": 0.02, "p99_factor": 4.0})
        g, f = slo_gate(f"diurnal/{name}", s, spec, baseline)
        curve.append({"step": name, "rate_factor": factor,
                      "summary": s, "slo": g})
        fails.extend(f)
    return {"phase": "diurnal", "curve": curve, "failures": fails}


def phase_hot_shift(cluster, args, policy, total_keys, mix, acked,
                    baseline) -> Dict:
    """Hot-SHARD skew: 90% of ops target ~20% of the shards (a
    contiguous ring arc, i.e. a specific subset of leader nodes);
    mid-phase the arc jumps to the opposite side of the ring."""
    import random as _random

    rng = _random.Random(args.seed + 17)
    arc = max(1, cluster.shards // 5)
    hot_a = list(range(0, arc))
    hot_b = [(s + cluster.shards // 2) % cluster.shards
             for s in range(arc)]
    hot = {"cur": hot_a}
    per_shard = max(1, total_keys // cluster.shards)

    def gid_source() -> int:
        if rng.random() < 0.9:
            s = rng.choice(hot["cur"])
        else:
            s = rng.randrange(cluster.shards)
        return s + cluster.shards * rng.randrange(per_shard)

    def shifter():
        time.sleep(args.duration / 2)
        hot["cur"] = hot_b
        log("  hot set SHIFTED to the opposite ring arc")

    t = threading.Thread(target=shifter, daemon=True)
    log(f"phase hot_shift: {args.rate}/s x {args.duration}s, hot arc "
        f"{arc}/{cluster.shards} shards, shift at t+{args.duration / 2:.1f}s")
    t.start()
    summary = run_fleet_phase(
        cluster, policy, args.rate, args.duration, total_keys,
        args.value_bytes, mix, args.seed + 7, args.max_inflight,
        gid_source=gid_source, acked=acked)
    t.join(timeout=5)
    spec = {"max_error_rate": 0.03, "p99_factor": 4.0}
    gates, fails = slo_gate("hot_shift", summary, spec, baseline)
    return {"phase": "hot_shift", "hot_arc_shards": arc,
            "summary": summary, "slo": gates, "failures": fails}


def phase_node_kill(cluster, args, policy, total_keys, mix, acked,
                    baseline) -> Dict:
    """SIGKILL a node mid-phase, keep serving, then restart it and
    time recovery. Reads fail over to surviving replicas (the router
    skips dead candidates); writes to the dead node's led shards error
    until it returns — the availability gate budgets exactly that."""
    victim = args.kill_node % cluster.nodes
    led_share = len(cluster.leaders_on(victim)) / cluster.shards
    put_share = mix.get("put", 0.0)
    kill_at = args.duration * 0.3

    killer = threading.Timer(kill_at, cluster.kill_node, args=(victim,))
    log(f"phase node_kill: {args.rate}/s x {args.duration}s, SIGKILL "
        f"node{victim} at t+{kill_at:.1f}s (leads "
        f"{led_share:.0%} of shards)")
    killer.start()
    summary = run_fleet_phase(
        cluster, policy, args.rate, args.duration, total_keys,
        args.value_bytes, mix, args.seed + 11, args.max_inflight)
    killer.cancel()

    t0 = time.monotonic()
    cluster.restart_node(victim)
    affected = [s for s in range(cluster.shards)
                if victim in cluster.replica_nodes(s)]
    cluster.wait_converged(affected, timeout=90.0)
    recovery_sec = time.monotonic() - t0

    # budget: writes to the victim's led shards are gone for ~70% of
    # the phase; reads mostly fail over. 3x slack on the write share
    # covers in-flight losses at the kill edge + failover latency.
    # p99 slack is ABSOLUTE: the failover tail is a detection floor
    # (in-flight ops at the kill edge ride out a connect/read timeout
    # before the router retargets) that doesn't scale with baseline
    # latency — a factor-only bound gets arbitrarily tight when the
    # unloaded baseline is fast.
    budget = min(0.5, 3.0 * led_share * put_share + 0.05)
    spec = {"max_error_rate": round(budget, 3), "p99_factor": 6.0,
            "p99_slack_ms": 250.0}
    gates, fails = slo_gate("node_kill", summary, spec, baseline)
    gates["killed_node"] = victim
    gates["led_share"] = round(led_share, 3)
    gates["recovery_sec"] = round(recovery_sec, 2)
    log(f"  node{victim} restarted; {len(affected)} shards reconverged "
        f"in {recovery_sec:.1f}s")
    return {"phase": "node_kill", "summary": summary, "slo": gates,
            "failures": fails}


def phase_drain(cluster, args, policy, total_keys, mix, acked,
                baseline) -> Dict:
    """Live-drain a node's led shards under load (pause → level →
    promote(epoch+1) → repoint → demote per shard), then read every
    acked put back: zero acked-write loss."""
    victim = args.drain_node % cluster.nodes
    n_led = len(cluster.leaders_on(victim))
    drain_result: Dict = {}
    drain_err: List[str] = []

    def drainer():
        time.sleep(args.duration * 0.2)
        try:
            drain_result.update(cluster.drain_node(victim))
        except Exception as e:
            drain_err.append(f"drain: {type(e).__name__}: {e}")

    t = threading.Thread(target=drainer, daemon=True)
    log(f"phase drain: {args.rate}/s x {args.duration}s, draining "
        f"node{victim} ({n_led} led shards) under load")
    t.start()
    phase_acked: set = set()
    summary = run_fleet_phase(
        cluster, policy, args.rate, args.duration, total_keys,
        args.value_bytes, mix, args.seed + 13, args.max_inflight,
        acked=phase_acked)
    t.join(timeout=120)
    acked |= phase_acked
    rb = readback_acked(cluster, phase_acked, args.value_bytes)

    # same absolute slack rationale as node_kill: gets racing a
    # shard's promote/re-teach window ride one failover hop
    spec = {"max_error_rate": 0.15, "p99_factor": 6.0,
            "p99_slack_ms": 250.0}
    gates, fails = slo_gate("drain", summary, spec, baseline)
    fails.extend(drain_err)
    if t.is_alive():
        fails.append("drain: drainer still running after the phase")
    if not drain_err and drain_result.get("shards_moved", 0) != n_led:
        fails.append(f"drain: moved {drain_result.get('shards_moved')} "
                     f"of {n_led} led shards")
    if cluster.leaders_on(victim):
        fails.append(f"drain: node{victim} still leads "
                     f"{cluster.leaders_on(victim)}")
    if rb["lost"]:
        fails.append(f"drain: {rb['lost']} acked puts lost "
                     f"(of {rb['sampled']} sampled)")
    gates["drained_node"] = victim
    gates["acked_readback"] = rb
    drain_result.pop("moves", None)  # artifact size: counts only
    return {"phase": "drain", "summary": summary, "drain": drain_result,
            "slo": gates, "failures": fails}


def phase_cdc_burst(cluster, args, policy, total_keys, mix, acked,
                    baseline, root) -> Dict:
    """A CDC ingest burst through the broker into a shard subset while
    serving: exactly-once drain (applied == produced, zero dup_skipped)
    against the CURRENT leaders (drain may have moved them)."""
    from rocksplicator_tpu.kafka.network import BrokerServer

    burst_shards = list(range(min(cluster.shards, 2 * cluster.nodes)))
    topic = "fleet_cdc"
    broker = BrokerServer(
        data_dir=os.path.join(root, "fleet_broker")).start()
    fails: List[str] = []
    try:
        bport = broker.port

        async def bcall(method: str, **a):
            return await cluster.pool.call(
                "127.0.0.1", bport, method, a, timeout=15.0)

        cluster.ioloop.run_sync(
            bcall("broker_create_topic", topic=topic,
                  num_partitions=cluster.shards), timeout=20)
        for s in burst_shards:
            cluster.admin(
                cluster.leader_of[s], "start_message_ingestion",
                db_name=db_name_of(s), topic_name=topic,
                kafka_broker_serverset_path=f"broker://127.0.0.1:{bport}",
                timeout=30.0)

        before = cluster.counter_sums(("kafka.cdc.",))
        produced = [0]
        stop = threading.Event()

        def producer():
            i = 0
            target = args.cdc_records * len(burst_shards)
            while i < target and not stop.is_set():
                burst = min(64, target - i)
                msgs = []
                for _ in range(burst):
                    s = burst_shards[i % len(burst_shards)]
                    msgs.append((s, b"fcdc%08d" % i,
                                 _cdc_value(i, args.cdc_value_bytes)))
                    i += 1

                async def send():
                    await asyncio.gather(*[
                        bcall("broker_produce", topic=topic, partition=p,
                              key=k, value=v,
                              timestamp_ms=int(time.time() * 1000))
                        for (p, k, v) in msgs])

                cluster.ioloop.run_sync(send(), timeout=30)
                produced[0] += burst

        t = threading.Thread(target=producer, daemon=True)
        log(f"phase cdc_burst: {args.cdc_records} rec x "
            f"{len(burst_shards)} shards through the broker + "
            f"{args.rate}/s serving x {args.duration}s")
        t.start()
        summary = run_fleet_phase(
            cluster, policy, args.rate, args.duration, total_keys,
            args.value_bytes, mix, args.seed + 19, args.max_inflight,
            acked=acked)
        t.join(timeout=60)
        stop.set()
        if t.is_alive():
            fails.append("cdc_burst: producer wedged")

        deadline = time.monotonic() + args.cdc_drain_timeout
        while time.monotonic() < deadline:
            delta = cluster.counter_sums(("kafka.cdc.",))
            applied = (delta.get("kafka.cdc.records_applied", 0)
                       - before.get("kafka.cdc.records_applied", 0))
            if applied >= produced[0]:
                break
            time.sleep(0.25)
        delta = cluster.counter_sums(("kafka.cdc.",))
        applied = int(delta.get("kafka.cdc.records_applied", 0)
                      - before.get("kafka.cdc.records_applied", 0))
        dups = int(delta.get("kafka.cdc.dup_skipped", 0)
                   - before.get("kafka.cdc.dup_skipped", 0))
        for s in burst_shards:
            with contextlib.suppress(Exception):
                cluster.admin(cluster.leader_of[s],
                              "stop_message_ingestion",
                              db_name=db_name_of(s), timeout=30.0)

        if applied != produced[0]:
            fails.append(f"cdc_burst: applied {applied} != produced "
                         f"{produced[0]} (exactly-once drain)")
        if dups:
            fails.append(f"cdc_burst: {dups} dup_skipped (should be 0)")
        # the CDC ingest shares the grouped-commit write path with the
        # serving load, so p99 gets a wide berth — the exactly-once
        # drain above is this phase's real gate
        spec = {"max_error_rate": 0.03, "p99_factor": 8.0}
        gates, f = slo_gate("cdc_burst", summary, spec, baseline)
        fails.extend(f)
        gates["cdc"] = {"produced": produced[0], "applied": applied,
                        "dup_skipped": dups,
                        "burst_shards": len(burst_shards)}
        return {"phase": "cdc_burst", "summary": summary, "slo": gates,
                "failures": fails}
    finally:
        broker.stop()


def phase_cooldown(cluster, args, policy, total_keys, mix, acked,
                   baseline) -> Dict:
    """Return to half the baseline rate, then require FULL fleet
    convergence (every replica of every shard at one seq) and a clean
    readback of every acked put across the whole timeline."""
    rate = args.rate * 0.5
    log(f"phase cooldown: {rate:.0f}/s x {args.duration}s + fleet "
        "convergence")
    summary = run_fleet_phase(
        cluster, policy, rate, args.duration, total_keys,
        args.value_bytes, mix, args.seed + 23, args.max_inflight,
        acked=acked)
    spec = {"max_error_rate": 0.01, "p99_factor": 3.0}
    gates, fails = slo_gate("cooldown", summary, spec, baseline)
    try:
        gates["convergence_sec"] = round(
            cluster.wait_converged(timeout=90.0), 2)
    except RuntimeError as e:
        fails.append(f"cooldown: {e}")
    rb = readback_acked(cluster, acked, args.value_bytes)
    gates["acked_readback"] = rb
    if rb["lost"]:
        fails.append(f"cooldown: {rb['lost']} acked puts lost across "
                     f"the timeline (of {rb['sampled']} sampled)")
    return {"phase": "cooldown", "summary": summary, "slo": gates,
            "failures": fails}


def run_timeline(args, root: str) -> Dict:
    from rocksplicator_tpu.rpc.router import ReadPolicy

    mix = parse_mix(args.mix)
    total_keys = args.shards * args.preload_keys
    policy = ReadPolicy.follower_ok(args.max_lag)
    phases = [p.strip() for p in args.phases.split(",") if p.strip()]
    acked: set = set()

    log(f"fleet: {args.nodes} nodes x {args.shards} shards (RF="
        f"{REPLICATION_FACTOR}), {total_keys} keys, phases: "
        + ",".join(phases))
    cluster = FleetCluster(
        root, args.nodes, args.shards, args.preload_keys,
        args.value_bytes, args.write_window, args.read_info_ttl_ms,
        args.transport, args.executor_threads, with_admin=True)
    try:
        cluster.wait_catchup(total_keys)
        baseline: Optional[Dict] = None
        timeline: List[Dict] = []
        failures: List[str] = []
        for name in phases:
            if name == "baseline":
                rec = phase_baseline(cluster, args, policy, total_keys,
                                     mix, acked)
                baseline = rec["summary"]
            elif name == "diurnal":
                rec = phase_diurnal(cluster, args, policy, total_keys,
                                    mix, acked, baseline)
            elif name == "hot_shift":
                rec = phase_hot_shift(cluster, args, policy, total_keys,
                                      mix, acked, baseline)
            elif name == "node_kill":
                rec = phase_node_kill(cluster, args, policy, total_keys,
                                      mix, acked, baseline)
            elif name == "drain":
                rec = phase_drain(cluster, args, policy, total_keys,
                                  mix, acked, baseline)
            elif name == "cdc_burst":
                rec = phase_cdc_burst(cluster, args, policy, total_keys,
                                      mix, acked, baseline, root)
            elif name == "cooldown":
                rec = phase_cooldown(cluster, args, policy, total_keys,
                                     mix, acked, baseline)
            else:
                raise ValueError(f"unknown phase {name!r}")
            rec["cluster_stats"] = snapshot(cluster)
            failures.extend(rec.pop("failures"))
            timeline.append(rec)
        return {
            "bench": "fleet_bench",
            "topology": {
                "nodes": args.nodes, "shards": args.shards,
                "replication_factor": REPLICATION_FACTOR,
                "placement": "interleaved ring: leader of s = s % N, "
                             "followers the next two ring nodes",
                "pull_mux": os.environ.get("RSTPU_PULL_MUX", ""),
            },
            "config": {
                "rate": args.rate, "phase_duration": args.duration,
                "mix": args.mix, "preload_keys": args.preload_keys,
                "value_bytes": args.value_bytes,
                "max_lag": args.max_lag, "seed": args.seed,
            },
            "phases": timeline,
            "final_cluster_stats": cluster.cluster_stats(),
            "failures": failures,
        }
    finally:
        cluster.stop()


# ---------------------------------------------------------------------------
# mux A/B: RSTPU_PULL_MUX=1 vs 0 over fresh fleets, idle-window frames
# ---------------------------------------------------------------------------


def _frames_and_parked(cluster: FleetCluster) -> Tuple[float, float]:
    """One scrape pass: fleet frames total (sent+received) and parked
    long-polls summed over the per-node gauges. The parked gauge rides
    the same scrape as the frame counters, so the idle window pays
    only the bracketing scrapes' own frames (~2/node)."""
    frames = 0.0
    parked = 0.0
    for i in range(cluster.nodes):
        st = cluster.scrape_node(i)
        for k, v in (st.get("counters") or {}).items():
            if k.startswith(("rpc.frames_sent", "rpc.frames_received")):
                frames += v["total"]
        for k, v in (st.get("gauges") or {}).items():
            if k.startswith("replicator.parked_longpolls"):
                parked += float(v)
    return frames, parked


def run_mux_ab(args, root: str) -> Dict:
    """Interleaved mux-on vs mux-off over fresh fleets: the load
    window measures applied put throughput + get p99 + acked readback;
    the IDLE window (driver silent) measures the replication plane's
    own steady-state cost — frames/sec and parked long-polls per node,
    the two quantities the mux collapses."""
    from rocksplicator_tpu.rpc.router import ReadPolicy

    mix = parse_mix("get=0.5,put=0.5")
    total_keys = args.ab_shards * args.preload_keys
    rep_n = [0]

    def arm(mux: str):
        def thunk() -> Dict:
            rep_n[0] += 1
            workdir = os.path.join(root, f"ab_{mux}_{rep_n[0]}")
            os.makedirs(workdir, exist_ok=True)
            env = {"RSTPU_PULL_MUX": "1" if mux == "mux_on" else "0"}
            with _bench_env(**env):
                cluster = FleetCluster(
                    workdir, args.ab_nodes, args.ab_shards,
                    args.preload_keys, args.value_bytes,
                    args.write_window, args.read_info_ttl_ms,
                    args.transport, args.executor_threads,
                    with_admin=False, extra_env=env)
                try:
                    cluster.wait_catchup(total_keys)
                    acked: set = set()
                    res = cluster.ioloop.run_sync(
                        _run_open_loop(
                            cluster, ReadPolicy.follower_ok(args.max_lag),
                            args.ab_rate, args.ab_load_sec, total_keys,
                            args.value_bytes, mix, args.seed + rep_n[0],
                            args.max_inflight, acked_puts=acked),
                        timeout=args.ab_load_sec + 240)
                    summary = res.summarize(args.ab_rate,
                                            args.ab_load_sec)
                    time.sleep(1.0)  # drain the replication tail
                    f0, p0 = _frames_and_parked(cluster)
                    t0 = time.monotonic()
                    time.sleep(args.ab_idle_sec)
                    f1, p1 = _frames_and_parked(cluster)
                    idle = time.monotonic() - t0
                    rb = readback_acked(cluster, acked,
                                        args.value_bytes)
                    mc = cluster.counter_sums(("replicator.mux_",))
                    put = summary["ops"].get("put") or {}
                    return {
                        "idle_frames_per_node_sec": round(
                            (f1 - f0) / idle / cluster.nodes, 2),
                        "parked_per_node": round(
                            (p0 + p1) / 2 / cluster.nodes, 2),
                        "applied_puts_per_sec": round(
                            put.get("count", 0) / args.ab_load_sec, 1),
                        "get_p99_ms": (summary["ops"].get("get")
                                       or {}).get("p99_ms"),
                        "acked_loss": rb["lost"],
                        "acked_sampled": rb["sampled"],
                        "value_mismatches": summary["value_mismatches"],
                        "mux_pulls": mc.get("replicator.mux_pulls", 0.0),
                        "mux_fallbacks": mc.get(
                            "replicator.mux_fallbacks", 0.0),
                    }
                finally:
                    cluster.stop()

        return thunk

    log(f"mux A/B: {args.ab_nodes} nodes x {args.ab_shards} shards, "
        f"{args.ab_reps} reps, load {args.ab_rate}/s x "
        f"{args.ab_load_sec}s, idle window {args.ab_idle_sec}s")
    ab = run_interleaved(
        [("mux_off", arm("mux_off")), ("mux_on", arm("mux_on"))],
        reps=args.ab_reps, key="idle_frames_per_node_sec",
        baseline="mux_off", higher_is_better=False, log=log)
    return {
        "bench": "fleet_mux_ab",
        "topology": {"nodes": args.ab_nodes, "shards": args.ab_shards,
                     "replication_factor": REPLICATION_FACTOR},
        "config": {"rate": args.ab_rate, "load_sec": args.ab_load_sec,
                   "idle_sec": args.ab_idle_sec,
                   "frames_factor": args.ab_frames_factor,
                   "parked_factor": args.ab_parked_factor},
        "ab": ab,
        "failures": mux_ab_failures(ab, args.ab_frames_factor,
                                    args.ab_parked_factor,
                                    args.ab_p99_factor),
    }


def _median(vals: List[float]) -> Optional[float]:
    vals = sorted(v for v in vals if v is not None)
    if not vals:
        return None
    return percentile(vals, 50.0)


def mux_ab_failures(ab: Dict, frames_factor: float,
                    parked_factor: float,
                    p99_factor: float = 1.5) -> List[str]:
    fails: List[str] = []
    samples = ab.get("samples") or {}
    for armname in ("mux_off", "mux_on"):
        if not samples.get(armname):
            fails.append(f"no completed {armname} rep")
    for armname, reps in samples.items():
        for s in reps:
            if s["acked_loss"]:
                fails.append(f"{armname}: {s['acked_loss']} acked puts "
                             f"lost (of {s['acked_sampled']})")
            if s["value_mismatches"]:
                fails.append(f"{armname}: {s['value_mismatches']} "
                             "value mismatches")
    for s in samples.get("mux_on") or []:
        if s["mux_pulls"] <= 0:
            fails.append("mux_on arm recorded zero mux pulls")
        if s["mux_fallbacks"] > 0:
            fails.append(f"mux_on arm fell back per-shard "
                         f"{int(s['mux_fallbacks'])}x")
    for s in samples.get("mux_off") or []:
        if s["mux_pulls"] > 0:
            fails.append("mux_off arm recorded mux pulls")
    if fails:
        return fails

    def med(armname, field):
        return _median([s[field] for s in samples[armname]])

    off_f, on_f = med("mux_off", "idle_frames_per_node_sec"), \
        med("mux_on", "idle_frames_per_node_sec")
    if on_f is None or off_f is None or on_f <= 0:
        fails.append("frame medians missing/zero")
    elif off_f / on_f < frames_factor:
        fails.append(f"idle frames/node only {off_f / on_f:.1f}x lower "
                     f"with mux ({off_f} -> {on_f}), need >= "
                     f"{frames_factor}x")
    off_p, on_p = med("mux_off", "parked_per_node"), \
        med("mux_on", "parked_per_node")
    if on_p is None or off_p is None or on_p <= 0:
        fails.append("parked-longpoll medians missing/zero")
    elif off_p / on_p < parked_factor:
        fails.append(f"parked long-polls/node only {off_p / on_p:.1f}x "
                     f"lower with mux ({off_p} -> {on_p}), need >= "
                     f"{parked_factor}x")
    off_a, on_a = med("mux_off", "applied_puts_per_sec"), \
        med("mux_on", "applied_puts_per_sec")
    if off_a and on_a and (on_a < 0.75 * off_a or off_a < 0.75 * on_a):
        fails.append(f"applied put throughput not equal: off {off_a}/s "
                     f"vs on {on_a}/s")
    off_p99, on_p99 = med("mux_off", "get_p99_ms"), \
        med("mux_on", "get_p99_ms")
    if off_p99 is not None and on_p99 is not None \
            and on_p99 > off_p99 * p99_factor + 1.0:
        fails.append(f"get p99 worse with mux: {off_p99}ms -> "
                     f"{on_p99}ms")
    return fails


# ---------------------------------------------------------------------------
# entrypoint
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--nodes", type=int, default=10)
    p.add_argument("--shards", type=int, default=100)
    p.add_argument("--preload_keys", type=int, default=100,
                   help="keys preloaded PER SHARD")
    p.add_argument("--value_bytes", type=int, default=128)
    p.add_argument("--write_window", type=int, default=64)
    p.add_argument("--read_info_ttl_ms", type=int, default=1500)
    p.add_argument("--executor_threads", type=int, default=2)
    p.add_argument("--transport", default="tcp", choices=["tcp", "uds"])
    p.add_argument("--rate", type=float, default=600.0)
    p.add_argument("--duration", type=float, default=5.0,
                   help="seconds per timeline phase")
    p.add_argument("--mix", default="get=0.75,put=0.15,"
                                    "multi_get=0.05,scan=0.05")
    p.add_argument("--max_lag", type=int, default=4096)
    p.add_argument("--seed", type=int, default=1)
    p.add_argument("--max_inflight", type=int, default=384)
    p.add_argument("--phases",
                   default="baseline,diurnal,hot_shift,node_kill,"
                           "drain,cdc_burst,cooldown")
    p.add_argument("--kill_node", type=int, default=1)
    p.add_argument("--drain_node", type=int, default=2)
    p.add_argument("--cdc_records", type=int, default=150,
                   help="CDC records per burst shard")
    p.add_argument("--cdc_value_bytes", type=int, default=200)
    p.add_argument("--cdc_drain_timeout", type=float, default=60.0)
    p.add_argument("--ab", action="store_true",
                   help="run the mux on/off A/B instead of the timeline")
    p.add_argument("--ab_nodes", type=int, default=8)
    p.add_argument("--ab_shards", type=int, default=64)
    p.add_argument("--ab_reps", type=int, default=2)
    p.add_argument("--ab_rate", type=float, default=400.0)
    p.add_argument("--ab_load_sec", type=float, default=6.0)
    p.add_argument("--ab_idle_sec", type=float, default=6.0)
    p.add_argument("--ab_frames_factor", type=float, default=5.0,
                   help="required idle frames/node reduction (mux off "
                        "/ mux on); the ring layout predicts ~S/N")
    p.add_argument("--ab_parked_factor", type=float, default=5.0)
    p.add_argument("--ab_p99_factor", type=float, default=1.5,
                   help="get p99 with mux may be at most this factor "
                        "of the mux-off median (+1ms slack); smokes "
                        "with short windows and few reps relax it")
    p.add_argument("--out")
    args = p.parse_args(argv)

    with tempfile.TemporaryDirectory(prefix="fleet_bench_") as root:
        if args.ab:
            result = run_mux_ab(args, root)
        else:
            result = run_timeline(args, root)
        result["host_calibration"] = host_calibration(root)
        return emit_gated_artifact(
            result, args.out, result["bench"], log=log)


if __name__ == "__main__":
    sys.exit(main())
