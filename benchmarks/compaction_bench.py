#!/usr/bin/env python
"""Compaction-scheduler A/B: a mixed-load engine slice of the macro-bench.

Reuses the macro-bench's workload generators — seeded zipfian key
popularity, open-loop Poisson arrivals with latency measured from the
INTENDED arrival (coordinated-omission fix), a get/put mix — and drives
them straight at ONE engine with background compaction under write-heavy
pressure (small memtable + low L0 triggers: real L0 debt accumulates),
interleaving the workload-adaptive compaction scheduler ON vs OFF
(``DBOptions.compaction_scheduler`` — the same switch
RSTPU_COMPACTION_SCHED=0 flips process-wide) at the same offered
throughput. This is where the scheduler's effect lives: get p99 under
compaction churn, write-stall ms, and the debt drain the round-14
gauges measure.

Per mode the artifact records get/put p50/p99, achieved throughput,
write-stall totals, end-of-phase + settled compaction debt (drain
rate), the scheduler counters (``compaction.sched_picks``,
``compaction.yields``, ``compaction.subcompactions``), and the slowest
tail-kept write traces attributing any remaining slow writes. Loud
failure gates: a scheduler-on phase must carry picks, both arms must
carry a get p99, and every sampled get must return a value from the
deterministic preload/put set (zero acked-write loss).

`make compaction-bench-smoke` runs the sub-minute configuration;
tier-1 asserts the artifact shape (tests/test_compaction_scheduler.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional

from benchmarks.ab_runner import (emit_gated_artifact, host_calibration,
                                  run_interleaved, sched_ab_failures)
from benchmarks.macro_bench import (ZipfianGenerator, op_stream, parse_mix,
                                    percentile, poisson_arrivals)

DEFAULT_MIX = "get=0.55,put=0.45"


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def key_of(gid: int) -> bytes:
    return b"k%08d" % gid


def preload_value(gid: int, n: int) -> bytes:
    v = b"l%08d." % gid
    return (v * (n // len(v) + 1))[:n]


def put_value(gid: int, n: int) -> bytes:
    v = b"p%08d." % gid
    return (v * (n // len(v) + 1))[:n]


def _counters(prefix: str) -> float:
    from rocksplicator_tpu.utils.stats import Stats

    state = Stats.get().export_state()["counters"]
    return sum(v["total"] for k, v in state.items() if k.startswith(prefix))


def _stall_totals() -> Dict[str, float]:
    from rocksplicator_tpu.utils.stats import Stats

    state = Stats.get().export_state()["metrics"]
    rec = state.get("storage.write_stall_ms") or {}
    tot = rec.get("totals") or rec  # exact all-time state
    return {
        "sum_ms": float(tot.get("sum", 0.0)),
        "count": float(tot.get("count", 0)),
    }


def _tail_traces(limit: int = 3) -> List[Dict]:
    """Slowest tail-kept roots on the trace plane — the attribution for
    any remaining slow writes the scheduler did not prevent."""
    from rocksplicator_tpu.observability.collector import SpanCollector

    roots = [
        s for s in SpanCollector.get().snapshot()
        if s.get("annotations", {}).get("tail_kept")
        or s.get("name") in ("storage.flush", "storage.compaction")
    ]
    roots.sort(key=lambda s: -float(s.get("duration_ms") or 0.0))
    return [
        {"name": s["name"], "duration_ms": s.get("duration_ms"),
         "annotations": {k: v for k, v in s.get("annotations", {}).items()
                         if not isinstance(v, (bytes,))}}
        for s in roots[:limit]
    ]


def run_phase(root: str, mode: str, args, seed: int) -> Dict:
    """One mode's phase: fresh DB, preload, open-loop mixed load, then
    a settle window measuring debt drain. Counters are process-global:
    report DELTAS across the phase."""
    from rocksplicator_tpu.storage.engine import DB, DBOptions
    from rocksplicator_tpu.storage.records import WriteBatch

    sched_on = mode == "sched_on"
    # bench-scale subcompaction threshold: the production floor (32k
    # entries per slice) is sized for 64MB files; the bench's small
    # target files would never slice, leaving the parallel-merge half
    # of the scheduler unmeasured (recorded in config)
    import rocksplicator_tpu.storage.native_compaction as nc

    nc.MIN_SLICE_ENTRIES = args.min_slice_entries
    opts = DBOptions(
        background_compaction=True,
        compaction_scheduler=sched_on,
        memtable_bytes=args.memtable_kb * 1024,
        level0_compaction_trigger=4,
        level0_slowdown_writes_trigger=8,
        level0_stop_writes_trigger=16,
        target_file_bytes=args.target_file_kb * 1024,
        max_bytes_for_level_base=args.level_base_kb * 1024,
        max_subcompactions=0 if sched_on else 1,
        compaction_budget_bytes_per_sec=(
            args.budget_bytes if sched_on else 0),
    )
    db_dir = os.path.join(root, f"db-{mode}-{seed}")
    mix = parse_mix(args.mix)
    total_keys = args.keys
    base_picks = _counters("compaction.sched_picks")
    base_yields = _counters("compaction.yields")
    base_sub = _counters("compaction.subcompactions")
    base_stall = _stall_totals()

    db = DB(db_dir, opts)
    try:
        batch = None
        for gid in range(total_keys):
            if batch is None:
                batch = WriteBatch()
            batch.put(key_of(gid), preload_value(gid, args.value_bytes))
            if batch.count() >= 64:
                db.write(batch)
                batch = None
        if batch is not None:
            db.write(batch)
        db.flush()

        arrivals = poisson_arrivals(args.rate, args.duration, seed)
        ops = op_stream(mix, len(arrivals), seed + 1)
        zipf = ZipfianGenerator(total_keys, seed=seed + 2)
        gids = [zipf.next() for _ in arrivals]
        lat: Dict[str, List[float]] = {"get": [], "put": []}
        errors = {"get": 0, "put": 0}
        mismatches = [0]
        lat_lock = threading.Lock()
        put_seq = [0]

        def one_op(intended: float, op: str, gid: int) -> None:
            try:
                if op == "put":
                    with lat_lock:
                        put_seq[0] += 1
                        sync = (put_seq[0] % args.sync_every) == 0
                    db.write(WriteBatch().put(
                        key_of(gid), put_value(gid, args.value_bytes)),
                        sync=sync)
                else:
                    got = db.get(key_of(gid))
                    if got not in (preload_value(gid, args.value_bytes),
                                   put_value(gid, args.value_bytes)):
                        with lat_lock:
                            mismatches[0] += 1
            except Exception:
                with lat_lock:
                    errors[op] += 1
                return
            done = time.monotonic()
            with lat_lock:
                lat[op].append((done - intended) * 1000.0)

        pool = ThreadPoolExecutor(max_workers=args.workers,
                                  thread_name_prefix=f"cb-{mode}")
        t0 = time.monotonic()
        futs = []
        for off, op, gid in zip(arrivals, ops, gids):
            delay = (t0 + off) - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            futs.append(pool.submit(one_op, t0 + off, op, gid))
        for f in futs:
            f.result()
        phase_sec = time.monotonic() - t0
        pool.shutdown()

        snap = db.metrics_snapshot(max_age=0.0)
        debt_end = sum(snap["compaction_debt_bytes"])
        # settle window: how fast does the engine drain the remaining
        # debt with the load gone?
        settle_t0 = time.monotonic()
        time.sleep(args.settle)
        snap2 = db.metrics_snapshot(max_age=0.0)
        debt_settled = sum(snap2["compaction_debt_bytes"])
        settle_sec = max(1e-6, time.monotonic() - settle_t0)

        # zero acked-write loss: every sampled key reads back a value
        # from the deterministic set
        for gid in range(0, total_keys, max(1, total_keys // 128)):
            got = db.get(key_of(gid))
            if got not in (preload_value(gid, args.value_bytes),
                           put_value(gid, args.value_bytes)):
                mismatches[0] += 1

        gets = sorted(lat["get"])
        puts = sorted(lat["put"])
        stall = _stall_totals()
        return {
            "mode": mode,
            "offered_per_sec": args.rate,
            "duration_sec": round(phase_sec, 2),
            "achieved_per_sec": round(
                (len(gets) + len(puts)) / max(phase_sec, 1e-6), 1),
            "get_count": len(gets),
            "put_count": len(puts),
            "errors": dict(errors),
            "value_mismatches": mismatches[0],
            "get_p50_ms": round(percentile(gets, 50), 3) if gets else None,
            "get_p99_ms": round(percentile(gets, 99), 3) if gets else None,
            "put_p50_ms": round(percentile(puts, 50), 3) if puts else None,
            "put_p99_ms": round(percentile(puts, 99), 3) if puts else None,
            "write_stall_ms_total": round(
                stall["sum_ms"] - base_stall["sum_ms"], 2),
            "write_stalls": int(stall["count"] - base_stall["count"]),
            "debt_bytes_end_of_load": int(debt_end),
            "debt_bytes_after_settle": int(debt_settled),
            "debt_drain_bytes_per_sec": int(
                max(0, debt_end - debt_settled) / settle_sec),
            "counters": {
                "compaction.sched_picks": int(
                    _counters("compaction.sched_picks") - base_picks),
                "compaction.yields": int(
                    _counters("compaction.yields") - base_yields),
                "compaction.subcompactions": int(
                    _counters("compaction.subcompactions") - base_sub),
            },
            "slow_write_traces": _tail_traces(),
        }
    finally:
        db.close()


def run_offline_subcompaction(root: str, args) -> Dict:
    """The compaction-throughput half of the A/B: ONE large compaction
    (4 overlapping sorted runs over ``offline_keys`` keys) timed
    unsliced vs key-range-sliced, no concurrent serving load — the
    regime subcompactions are designed for (the serving phase above
    deliberately stays below the slice floor: parallel fan-out on
    small merges was measured to steal serving CPU for nothing).
    Output equality is checksummed across both arms.

    Streaming (round 17) is pinned OFF here: this A/B measures the
    in-RAM path's key-range slicing, and at 1M entries the auto
    threshold would otherwise route both arms through the bounded-
    memory merge (neither would slice). The streamed-vs-in-RAM A/B
    lives in benchmarks/stream_merge_bench.py."""
    import rocksplicator_tpu.storage.stream_merge as sm

    base_sub = _counters("compaction.subcompactions")
    out: Dict = {"entries": 4 * args.offline_keys}
    sums = {}
    prev_stream = sm.STREAM_MODE_OVERRIDE
    sm.STREAM_MODE_OVERRIDE = "never"
    try:
        return _offline_arms(root, args, out, sums, base_sub)
    finally:
        sm.STREAM_MODE_OVERRIDE = prev_stream


def _offline_arms(root: str, args, out: Dict, sums: Dict,
                  base_sub: float) -> Dict:
    import hashlib

    from rocksplicator_tpu.storage.engine import DB, DBOptions

    # the sliced arm forces >=2 slices: auto (0) resolves to
    # min(4, cores) which on a single-core host is 1 — the arm would
    # never slice and the "never sliced" gate would blame the floor
    for mode, nsub in (("unsliced", 1),
                       ("sliced", max(2, min(4, os.cpu_count() or 1)))):
        from rocksplicator_tpu.storage.records import WriteBatch

        db_dir = os.path.join(root, f"offline-{mode}")
        db = DB(db_dir, DBOptions(
            memtable_bytes=1 << 30, compaction_scheduler=False,
            # keep the 4 overlapping L0 runs intact: inline auto
            # compaction at the L0 trigger would pre-merge them and
            # both arms would time a single-run no-op
            disable_auto_compaction=True,
            target_file_bytes=4 << 20, max_subcompactions=nsub))
        try:
            for rev in range(4):
                batch = None
                for gid in range(args.offline_keys):
                    if batch is None:
                        batch = WriteBatch()
                    batch.put(key_of(gid),
                              b"r%d." % rev + put_value(gid, 64))
                    if batch.count() >= 512:
                        db.write(batch)
                        batch = None
                if batch is not None:
                    db.write(batch)
                db.flush()
            input_bytes = sum(
                os.path.getsize(os.path.join(db.path, n))
                for files in db._levels for n in files)
            t0 = time.monotonic()
            db.compact_range()
            secs = time.monotonic() - t0
            h = hashlib.sha256()
            for k, v in db.new_iterator():
                h.update(k)
                h.update(v)
            sums[mode] = h.hexdigest()
            out[f"{mode}_sec"] = round(secs, 3)
            out[f"{mode}_mb_per_sec"] = round(
                input_bytes / 1e6 / max(secs, 1e-9), 2)
        finally:
            db.close()
    out["subcompactions"] = int(
        _counters("compaction.subcompactions") - base_sub)
    out["output_checksums_equal"] = sums["unsliced"] == sums["sliced"]
    out["speedup"] = round(out["unsliced_sec"] / max(out["sliced_sec"],
                                                     1e-9), 2)
    return out


class _RemoteTier:
    """In-process disaggregated compaction tier for the A/B: one
    coordinator, one stateless worker, a ``local://`` object store.
    Leaders attach per-db managers; the worker drains every db's jobs."""

    def __init__(self, root: str):
        from rocksplicator_tpu.cluster.coordinator import (
            CoordinatorClient, CoordinatorServer)
        from rocksplicator_tpu.compaction_remote import CompactionWorker

        self.server = CoordinatorServer(port=0, session_ttl=5.0)
        self._clients: List = []

        def client():
            c = CoordinatorClient("127.0.0.1", self.server.port)
            self._clients.append(c)
            return c

        self._client = client
        self.store_uri = f"local://{os.path.join(root, 'remote_store')}"
        self._stop = threading.Event()
        self.worker = CompactionWorker(
            client(), os.path.join(root, "remote_worker"),
            worker_id="bench-worker", poll_interval=0.02,
            heartbeat_interval=0.5)
        threading.Thread(target=self.worker.serve_forever,
                         args=(self._stop,), daemon=True).start()

    def attach(self, db, name: str):
        from rocksplicator_tpu.compaction_remote import (
            RemoteCompactionManager, RemoteDispatchPolicy)

        mgr = RemoteCompactionManager(
            name, db, self._client(), self.store_uri,
            policy=RemoteDispatchPolicy(
                enabled=True, size_floor_bytes=0, deadline_s=30.0,
                claim_wait_s=5.0, heartbeat_timeout_s=5.0,
                poll_interval_s=0.02),
            epoch_provider=lambda: 1)
        db.set_remote_compactor(mgr)
        return mgr

    def close(self) -> None:
        self._stop.set()
        for c in self._clients:
            try:
                c.close()
            except Exception:
                pass
        self.server.stop()


def run_remote_phase(root: str, mode: str, args, seed: int,
                     tier: _RemoteTier) -> Dict:
    """One arm of the tier on/off A/B: fresh db, preload, open-loop
    mixed load with background compaction, settle, then read where the
    compaction output bytes were written — serving node (local) or
    worker tier (offloaded)."""
    from rocksplicator_tpu.storage.engine import DB, DBOptions
    from rocksplicator_tpu.storage.records import WriteBatch

    opts = DBOptions(
        background_compaction=True,
        # scheduler pinned off in BOTH arms: the remote A/B measures
        # where the merge ran, not which pick policy chose it
        compaction_scheduler=False,
        memtable_bytes=args.memtable_kb * 1024,
        level0_compaction_trigger=4,
        level0_slowdown_writes_trigger=8,
        level0_stop_writes_trigger=16,
        target_file_bytes=args.target_file_kb * 1024,
        max_bytes_for_level_base=args.level_base_kb * 1024,
    )
    db = DB(os.path.join(root, f"db-{mode}-{seed}"), opts)
    mgr = None
    try:
        if mode == "tier_on":
            mgr = tier.attach(db, f"bench{mode}{seed}")
        batch = None
        for gid in range(args.keys):
            if batch is None:
                batch = WriteBatch()
            batch.put(key_of(gid), preload_value(gid, args.value_bytes))
            if batch.count() >= 64:
                db.write(batch)
                batch = None
        if batch is not None:
            db.write(batch)
        db.flush()

        mix = parse_mix(args.mix)
        arrivals = poisson_arrivals(args.rate, args.duration, seed)
        ops = op_stream(mix, len(arrivals), seed + 1)
        zipf = ZipfianGenerator(args.keys, seed=seed + 2)
        gids = [zipf.next() for _ in arrivals]
        lat: Dict[str, List[float]] = {"get": [], "put": []}
        errors = {"get": 0, "put": 0}
        mismatches = [0]
        lat_lock = threading.Lock()

        def one_op(intended: float, op: str, gid: int) -> None:
            try:
                if op == "put":
                    db.write(WriteBatch().put(
                        key_of(gid), put_value(gid, args.value_bytes)))
                else:
                    got = db.get(key_of(gid))
                    if got not in (preload_value(gid, args.value_bytes),
                                   put_value(gid, args.value_bytes)):
                        with lat_lock:
                            mismatches[0] += 1
            except Exception:
                with lat_lock:
                    errors[op] += 1
                return
            done = time.monotonic()
            with lat_lock:
                lat[op].append((done - intended) * 1000.0)

        pool = ThreadPoolExecutor(max_workers=args.workers,
                                  thread_name_prefix=f"crb-{mode}")
        t0 = time.monotonic()
        futs = []
        for off, op, gid in zip(arrivals, ops, gids):
            delay = (t0 + off) - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            futs.append(pool.submit(one_op, t0 + off, op, gid))
        for f in futs:
            f.result()
        phase_sec = time.monotonic() - t0
        pool.shutdown()
        time.sleep(args.settle)

        # zero acked-write loss across the offloaded installs
        for gid in range(0, args.keys, max(1, args.keys // 128)):
            got = db.get(key_of(gid))
            if got not in (preload_value(gid, args.value_bytes),
                           put_value(gid, args.value_bytes)):
                mismatches[0] += 1

        snap = db.metrics_snapshot(max_age=0.0)
        gets = sorted(lat["get"])
        puts = sorted(lat["put"])
        return {
            "mode": mode,
            "offered_per_sec": args.rate,
            "duration_sec": round(phase_sec, 2),
            "achieved_per_sec": round(
                (len(gets) + len(puts)) / max(phase_sec, 1e-6), 1),
            "get_count": len(gets),
            "put_count": len(puts),
            "errors": dict(errors),
            "value_mismatches": mismatches[0],
            "get_p50_ms": round(percentile(gets, 50), 3) if gets else None,
            "get_p99_ms": round(percentile(gets, 99), 3) if gets else None,
            "put_p99_ms": round(percentile(puts, 99), 3) if puts else None,
            "local_output_bytes": int(snap["bytes_compacted_local_total"]),
            "remote_offloaded_bytes": int(
                snap["remote_offloaded_bytes_total"]),
            "tier": (mgr.counters() if mgr is not None else None),
        }
    finally:
        db.close()


class _BenchPick:
    kind, level, score, reason = "l0", 0, 2.0, "bench"


def run_remote_determinism(root: str, args, tier: _RemoteTier) -> Dict:
    """Byte-identical installed generations: the SAME deterministic
    load compacted through the worker tier vs through the local path —
    the sorted sha256 set of live SSTs and the full iterator content
    hash must both match (same merge code, same parameters, so same
    bytes; this section proves it end to end through the object-store
    round trip)."""
    import hashlib

    from rocksplicator_tpu.compaction_remote import file_checksum
    from rocksplicator_tpu.storage.engine import DB, DBOptions
    from rocksplicator_tpu.storage.records import WriteBatch

    def build(tag: str):
        db = DB(os.path.join(root, f"det-{tag}"), DBOptions(
            memtable_bytes=8 * 1024, level0_compaction_trigger=100,
            background_compaction=False,
            target_file_bytes=args.target_file_kb * 1024))
        n = max(256, args.keys // 8)
        for gid in range(n):
            db.write(WriteBatch().put(
                key_of(gid), put_value(gid, args.value_bytes)))
            if gid % 50 == 0:
                db.flush()
        for gid in range(0, n, 7):
            db.write(WriteBatch().delete(key_of(gid)))
        db.flush()
        return db

    def files_sha(db) -> List[str]:
        return sorted(
            file_checksum(os.path.join(db.path, name))
            for level in db._levels for name in level)

    def content_sha(db) -> str:
        h = hashlib.sha256()
        for k, v in db.new_iterator():
            h.update(k)
            h.update(v)
        return h.hexdigest()

    db_remote = build("remote")
    db_local = build("local")
    try:
        mgr = tier.attach(db_remote, "benchdet")
        outcome = mgr.maybe_offload(_BenchPick())
        db_local.compact_range()
        remote_files = files_sha(db_remote)
        local_files = files_sha(db_local)
        return {
            "outcome": outcome,
            "files": len(remote_files),
            "file_checksums_equal": remote_files == local_files,
            "content_checksums_equal":
                content_sha(db_remote) == content_sha(db_local),
        }
    finally:
        db_remote.close()
        db_local.close()


def remote_ab_failures(samples: Dict[str, List[Dict]],
                       det: Dict) -> List[str]:
    """Loud gates for the tier on/off A/B: both arms completed with a
    get p99 and zero mismatches; the tier-on arm actually offloaded and
    its serving-node output bytes went to ~0 (the acceptance criterion);
    the tier-off arm offloaded nothing; the installed generations are
    byte-identical to the local path."""
    failures: List[str] = []
    for mode in ("tier_off", "tier_on"):
        if not samples.get(mode):
            failures.append(f"no completed {mode} rep")
    for mode, reps_data in samples.items():
        for s in reps_data:
            if s["value_mismatches"]:
                failures.append(
                    f"{mode}: {s['value_mismatches']} reads outside the "
                    f"deterministic value set (acked-write loss)")
            if s["get_p99_ms"] is None:
                failures.append(f"{mode}: no get p99 recorded")
    for s in samples.get("tier_on") or []:
        total = s["remote_offloaded_bytes"] + s["local_output_bytes"]
        if s["remote_offloaded_bytes"] <= 0:
            failures.append("tier_on rep offloaded zero bytes")
        elif s["local_output_bytes"] > 0.1 * total:
            failures.append(
                f"tier_on serving-node output bytes not ~0 "
                f"({s['local_output_bytes']} local of {total} total)")
    for s in samples.get("tier_off") or []:
        if s["remote_offloaded_bytes"]:
            failures.append("tier_off rep recorded offloaded bytes")
    if det.get("outcome") != "installed":
        failures.append(
            f"determinism section did not install remotely "
            f"({det.get('outcome')!r})")
    if not det.get("file_checksums_equal"):
        failures.append(
            "remote-installed SSTs differ byte-for-byte from the "
            "local path's")
    if not det.get("content_checksums_equal"):
        failures.append(
            "remote-installed content differs from the local path's")
    return failures


def run_remote_ab(args) -> int:
    """``--remote_ab``: interleaved tier-on/off under the same mixed
    load, plus the byte-identical determinism section. Artifact:
    benchmarks/results/compaction_remote_r18.json (full run)."""
    import shutil
    import tempfile

    root = tempfile.mkdtemp(prefix="rstpu-compact-remote-")
    t0 = time.monotonic()
    result: Dict = {
        "bench": "compaction_remote",
        "config": {
            "keys": args.keys, "value_bytes": args.value_bytes,
            "rate": args.rate, "duration": args.duration,
            "mix": args.mix, "reps": args.reps,
            "workers": args.workers, "memtable_kb": args.memtable_kb,
            "target_file_kb": args.target_file_kb,
            "level_base_kb": args.level_base_kb,
            "settle": args.settle, "seed": args.seed,
            "note": ("disaggregated compaction A/B: same offered load, "
                     "tier on vs off; tier-on serving-node compaction "
                     "output bytes must go to ~0 with the merge running "
                     "on the stateless worker"),
        },
        "host_calibration": host_calibration(root),
    }
    tier = _RemoteTier(root)
    rep_counter = [0]

    def variant(mode: str):
        def run() -> Dict:
            rep_counter[0] += 1
            seed = args.seed + 101 * rep_counter[0]
            return run_remote_phase(root, mode, args, seed, tier)
        return run

    try:
        # baseline FIRST (ratio_vs_tier_off reads naturally); lower get
        # p99 is better — the tier must not cost serving latency
        result["ab"] = run_interleaved(
            [("tier_off", variant("tier_off")),
             ("tier_on", variant("tier_on"))],
            reps=args.reps, key="get_p99_ms", higher_is_better=False,
            log=log)
        result["determinism"] = run_remote_determinism(root, args, tier)
    finally:
        tier.close()
        shutil.rmtree(root, ignore_errors=True)
    result["elapsed_sec"] = round(time.monotonic() - t0, 1)
    result["failures"] = remote_ab_failures(
        result["ab"]["samples"], result["determinism"])

    rc = emit_gated_artifact(result, args.out, "compaction_remote", log)
    if rc:
        return rc
    summ = result["ab"]["summary"]
    on = (result["ab"]["samples"].get("tier_on") or [{}])[-1]
    log(f"compaction_remote: get p99 tier_off="
        f"{(summ.get('tier_off') or {}).get('median')}ms tier_on="
        f"{(summ.get('tier_on') or {}).get('median')}ms; tier_on "
        f"local={on.get('local_output_bytes')}B "
        f"offloaded={on.get('remote_offloaded_bytes')}B")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--keys", type=int, default=8000)
    p.add_argument("--value_bytes", type=int, default=128)
    p.add_argument("--rate", type=float, default=1200.0,
                   help="offered ops/s (open-loop)")
    p.add_argument("--duration", type=float, default=6.0)
    p.add_argument("--mix", default=DEFAULT_MIX)
    p.add_argument("--reps", type=int, default=3)
    p.add_argument("--workers", type=int, default=8)
    p.add_argument("--settle", type=float, default=1.5,
                   help="post-load window measuring debt drain")
    p.add_argument("--memtable_kb", type=int, default=48)
    p.add_argument("--target_file_kb", type=int, default=128)
    p.add_argument("--level_base_kb", type=int, default=256)
    p.add_argument("--budget_bytes", type=int, default=0,
                   help="scheduler-on IO budget (0 = yield-only)")
    p.add_argument("--sync_every", type=int, default=4,
                   help="every Nth put is a sync write (foreground "
                        "fsync pressure the budget yields to)")
    p.add_argument("--min_slice_entries", type=int, default=32768,
                   help="subcompaction floor (entries per slice; the "
                        "production default): serving-phase merges "
                        "below it never slice — fan-out on small "
                        "merges steals serving CPU for nothing (PERF "
                        "round 16 measured it); the offline section's "
                        "large merge crosses it legitimately")
    p.add_argument("--offline_keys", type=int, default=60000,
                   help="keyspace for the offline sliced-vs-unsliced "
                        "one-shot compaction (4 overlapping L0 runs = "
                        "4x this many entries)")
    p.add_argument("--seed", type=int, default=1)
    p.add_argument("--remote_ab", action="store_true",
                   help="run the round-18 disaggregated-compaction "
                        "tier on/off A/B instead of the scheduler A/B: "
                        "same mixed load, compaction merges offloaded "
                        "to an in-process stateless worker via the job "
                        "ledger; gates tier-on local output bytes ~0 "
                        "and byte-identical installed generations")
    p.add_argument("--out")
    args = p.parse_args(argv)
    if args.remote_ab:
        return run_remote_ab(args)

    import shutil
    import tempfile

    root = tempfile.mkdtemp(prefix="rstpu-compact-bench-")
    t0 = time.monotonic()
    result: Dict = {
        "bench": "compaction_bench",
        "config": {
            "keys": args.keys, "value_bytes": args.value_bytes,
            "rate": args.rate, "duration": args.duration,
            "mix": args.mix, "reps": args.reps,
            "workers": args.workers, "memtable_kb": args.memtable_kb,
            "target_file_kb": args.target_file_kb,
            "level_base_kb": args.level_base_kb,
            "budget_bytes": args.budget_bytes,
            "sync_every": args.sync_every, "seed": args.seed,
            "min_slice_entries": args.min_slice_entries,
            "note": ("engine slice of the macro-bench mixed load: "
                     "zipfian keys, Poisson open-loop arrivals, "
                     "latency from intended arrival"),
        },
        "host_calibration": host_calibration(root),
    }
    rep_counter = [0]

    def variant(mode: str):
        def run() -> Dict:
            rep_counter[0] += 1
            seed = args.seed + 101 * rep_counter[0]
            return run_phase(root, mode, args, seed)
        return run

    try:
        # baseline FIRST (ratio_vs_sched_off reads naturally); lower
        # get p99 is better
        result["ab"] = run_interleaved(
            [("sched_off", variant("sched_off")),
             ("sched_on", variant("sched_on"))],
            reps=args.reps, key="get_p99_ms", higher_is_better=False,
            log=log)
        log("compaction_bench: offline sliced-vs-unsliced compaction "
            f"({4 * args.offline_keys} entries)")
        result["subcompaction_offline"] = run_offline_subcompaction(
            root, args)
        off = result["subcompaction_offline"]
        log(f"  unsliced {off['unsliced_sec']}s vs sliced "
            f"{off['sliced_sec']}s = {off['speedup']}x "
            f"({off['subcompactions']} slices)")
    finally:
        shutil.rmtree(root, ignore_errors=True)
    result["elapsed_sec"] = round(time.monotonic() - t0, 1)

    failures = sched_ab_failures(
        result["ab"]["samples"],
        picks_of=lambda ph: ph["counters"]["compaction.sched_picks"],
        mismatch_label=("reads outside the deterministic value set "
                       "(acked-write loss)"))
    off = result.get("subcompaction_offline") or {}
    if not off.get("output_checksums_equal"):
        failures.append(
            "offline sliced compaction output differs from unsliced")
    if off.get("subcompactions", 0) <= 0:
        failures.append(
            "offline sliced arm never sliced (floor too high for "
            "--offline_keys)")
    result["failures"] = failures

    rc = emit_gated_artifact(result, args.out, "compaction_bench", log)
    if rc:
        return rc
    summ = result["ab"]["summary"]
    log(f"compaction_bench: get p99 sched_off="
        f"{(summ.get('sched_off') or {}).get('median')}ms sched_on="
        f"{(summ.get('sched_on') or {}).get('median')}ms")
    return 0


if __name__ == "__main__":
    sys.exit(main())
