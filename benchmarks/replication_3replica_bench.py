#!/usr/bin/env python
"""BASELINE config #4-shaped benchmark: 3-replica semi-sync WAL tail.

Orchestrates a leader (replication mode 1: every write acks only after
a follower pulled it) and two followers tailing the leader's WAL over
the replication plane, on a selectable RPC byte layer:

- ``--transport tcp`` (default) — three OS processes over loopback TCP,
  the seed topology;
- ``--transport uds``  — the same three processes over the per-port
  unix-domain sockets (vectored sendmsg frame coalescing);
- ``--transport loopback`` — leader + followers COLOCATED in one
  process (``performance.py --role cluster``) over the in-process
  zero-copy loopback transport: the syscall-free ceiling.

``--transports tcp,uds,loopback --reps N`` runs the variants
INTERLEAVED (benchmarks/ab_runner.py) so same-host drift lands on every
byte layer equally, and reports median-to-median ratios vs the first.

Reports writes/s, MB/s, follower convergence, and acked-write loss.
(The config's "Kafka WAL-tail" consumer role is the CDC observer path,
covered by tests/test_admin.py + tests/test_kafka.py; this bench
measures the 3-replica semi-sync replication fabric itself.)

    python -m benchmarks.replication_3replica_bench \
        --shards 50 --keys 200 --value_bytes 1024 --transport uds

Reference harness shape: rocksdb_replicator/performance.cpp:57-207 (the
two-process original); config #4 in BASELINE.json adds the 3-replica +
WAL-tail consumer topology measured here.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import shutil
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.ab_runner import host_calibration, run_interleaved  # noqa: E402

TRANSPORTS = ("tcp", "uds", "loopback")


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def _spawn(role, port, db_dir, shards, keys, threads, value_bytes,
           upstream_port=0, mode=1, linger=60, trace=False,
           write_window=64, executor_threads=2, transport="tcp"):
    cmd = [
        sys.executable, "-m", "rocksplicator_tpu.replication.performance",
        "--role", role, "--port", str(port), "--db_dir", db_dir,
        "--num_shards", str(shards),
        "--num_write_threads", str(threads),
        "--num_keys_per_shard_thread", str(keys),
        "--value_size", str(value_bytes),
        "--replication_mode", str(mode),
        "--linger_sec", str(linger),
        "--write_window", str(write_window),
        # this bench targets small (2-4 core) CI hosts: a lean executor
        # avoids pure GIL thrash (serve is inline on the loop; executor
        # work is cold WAL scans and follower applies)
        "--executor_threads", str(executor_threads),
    ]
    if trace:
        cmd += ["--trace"]
    if upstream_port:
        cmd += ["--upstream_port", str(upstream_port)]
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               # explicit per-run policy: children (and their servers'
               # derived fast-path listeners) all agree by construction
               RSTPU_TRANSPORT=transport)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env,
    )


def host_roofline(tmp: str, value_bytes: int, n_writes: int = 2000) -> dict:
    """Same-host capability context (VERDICT r4 #5: the absolute
    writes/s is only interpretable against what THIS host can do).
    Measures (a) raw fsync rate — the floor under any durable ack —
    and (b) single-process engine write throughput with no replication,
    so the semi-sync number reads as a fraction of host capability
    rather than a bare absolute."""
    import tempfile as _tf

    from rocksplicator_tpu.storage.engine import DB, DBOptions

    # (a) fsync rate: append-and-fsync a small record repeatedly
    fd = os.open(os.path.join(tmp, "fsync_probe"),
                 os.O_CREAT | os.O_WRONLY | os.O_APPEND, 0o644)
    try:
        buf = b"x" * 4096
        n_fsync = 200
        t0 = time.monotonic()
        for _ in range(n_fsync):
            os.write(fd, buf)
            os.fsync(fd)
        fsync_per_sec = n_fsync / (time.monotonic() - t0)
    finally:
        os.close(fd)
    # (b) raw engine writes (no replication, async WAL)
    d = _tf.mkdtemp(dir=tmp)
    db = DB(os.path.join(d, "db"), DBOptions())
    val = b"v" * value_bytes
    t0 = time.monotonic()
    for i in range(n_writes):
        db.put(f"k{i:08d}".encode(), val)
    raw_elapsed = time.monotonic() - t0
    db.close()
    return {
        "fsync_per_sec": round(fsync_per_sec, 1),
        "engine_writes_per_sec_no_replication": round(
            n_writes / raw_elapsed, 1),
        "engine_mb_per_sec_no_replication": round(
            n_writes * value_bytes / raw_elapsed / 1e6, 2),
    }


class _LeaderReport:
    """Parsed leader stdout: throughput, acked count, trace block."""

    def __init__(self):
        self.mb = None
        self.elapsed = None
        self.acked = None
        self.total = None
        self.ack_window = None
        self.trace_lines = []
        self._in_trace = False

    def feed(self, line: str) -> bool:
        """Returns True once the throughput line landed (parse done)."""
        if line.startswith("TRACE-SLOWEST-WRITE-BEGIN"):
            self._in_trace = True
        if self._in_trace:
            self.trace_lines.append(line.rstrip("\n"))
            if line.startswith("TRACE-SLOWEST-WRITE-END"):
                self._in_trace = False
            return False
        m = re.search(
            r"TRACE-ACK-WINDOW sampled_ack_waits=(\d+) "
            r"max_overlapping=(\d+) max_window_depth=(\d+)", line)
        if m:
            self.ack_window = (int(m.group(1)), int(m.group(2)),
                               int(m.group(3)))
            return False
        m = re.search(r"leader acked (\d+)/(\d+) writes", line)
        if m:
            self.acked, self.total = int(m.group(1)), int(m.group(2))
            return False
        m = re.search(r"wrote ~([\d.]+) MB in ([\d.]+)s", line)
        if m:
            self.mb, self.elapsed = float(m.group(1)), float(m.group(2))
            return True
        return False


def run_once(args, transport: str, trace: bool = False) -> dict:
    """One full bench run on one transport; returns the results dict."""
    tmp = tempfile.mkdtemp(prefix=f"repl3-{transport}-")
    procs = []
    try:
        report = _LeaderReport()
        total_writes = args.keys * args.shards
        want = total_writes
        seqs = {0: 0, 1: 0}
        if transport == "loopback":
            # in-process colocation: ONE cluster process (the loopback
            # transport cannot cross OS processes — that's the point)
            t0 = time.monotonic()
            leader = _spawn("cluster", args.leader_port, tmp, args.shards,
                            args.keys, args.threads, args.value_bytes,
                            linger=120, trace=trace,
                            write_window=args.write_window,
                            transport=transport)
            procs.append(leader)
            for line in leader.stdout:
                log(f"[cluster] {line.rstrip()}")
                if report.feed(line):
                    break
            assert report.mb is not None, (
                "cluster leader never reported its write phase")
            deadline = time.monotonic() + 120
            for line in leader.stdout:
                m = re.search(r"follower(\d+) total seq: (\d+)", line)
                if m:
                    seqs[int(m.group(1))] = int(m.group(2))
                if "cluster converged" in line:
                    break
                if time.monotonic() > deadline:
                    break
            converge_sec = time.monotonic() - t0
        else:
            f1 = _spawn("follower", args.leader_port + 1,
                        os.path.join(tmp, "f1"), args.shards, args.keys,
                        args.threads, args.value_bytes,
                        upstream_port=args.leader_port, transport=transport)
            f2 = _spawn("follower", args.leader_port + 2,
                        os.path.join(tmp, "f2"), args.shards, args.keys,
                        args.threads, args.value_bytes,
                        upstream_port=args.leader_port, transport=transport)
            followers = [f1, f2]
            procs.extend(followers)
            time.sleep(2.0)
            t0 = time.monotonic()
            leader = _spawn("leader", args.leader_port,
                            os.path.join(tmp, "l"), args.shards, args.keys,
                            args.threads, args.value_bytes, linger=90,
                            trace=trace, write_window=args.write_window,
                            transport=transport)
            procs.append(leader)
            for line in leader.stdout:
                log(f"[leader] {line.rstrip()}")
                if report.feed(line):
                    break
            assert report.mb is not None, (
                "leader never reported its write phase")
            # watch follower convergence via their periodic seq dumps
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline and (
                    seqs[0] < want or seqs[1] < want):
                for idx, f in enumerate(followers):
                    line = f.stdout.readline()
                    if line:
                        m = re.search(r"follower total seq: (\d+)", line)
                        if m:
                            seqs[idx] = int(m.group(1))
                time.sleep(0.1)
            converge_sec = time.monotonic() - t0
        # the leader prints elapsed at 0.1s resolution: floor it so a
        # smoke-sized run can't divide by zero
        mb, elapsed = report.mb, max(report.elapsed, 0.05)
        acked = report.acked if report.acked is not None else total_writes
        results = {
            "transport": transport,
            "writes_acked": acked,
            "writes_total": total_writes,
            "leader_mb": mb,
            "leader_elapsed_s": elapsed,
            "writes_per_sec": round(total_writes / elapsed, 1),
            "acked_writes_per_sec": round(acked / elapsed, 1),
            "write_window": args.write_window,
            "mb_per_sec": round(mb / elapsed, 2),
            "follower_seqs": [seqs[0], seqs[1]],
            "both_followers_converged": bool(
                seqs[0] >= want and seqs[1] >= want),
            "convergence_sec_from_leader_start": round(converge_sec, 1),
            "acked_write_loss": max(0, want - min(seqs.values())),
        }
        if report.ack_window:
            results["ack_window_trace"] = {
                "sampled_ack_waits": report.ack_window[0],
                "max_overlapping_ack_waits": report.ack_window[1],
                "max_window_depth": report.ack_window[2],
            }
        if trace and report.trace_lines:
            results["slowest_write_trace"] = report.trace_lines
        return results
    finally:
        for p in procs:
            try:
                p.terminate()
                p.wait(timeout=10)
            except Exception:
                pass
        shutil.rmtree(tmp, ignore_errors=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--shards", type=int, default=50)
    ap.add_argument("--keys", type=int, default=200)
    ap.add_argument("--threads", type=int, default=2)
    ap.add_argument("--value_bytes", type=int, default=1024)
    ap.add_argument("--write_window", type=int, default=64,
                    help="leader max in-flight (unacked) writes per shard; "
                         "1 = the old serial blocking write path")
    ap.add_argument("--leader_port", type=int, default=29391)
    ap.add_argument("--transport", choices=TRANSPORTS, default="tcp",
                    help="RPC byte layer: tcp (3 processes, seed "
                         "topology), uds (3 processes, vectored unix "
                         "sockets), loopback (colocated single process, "
                         "in-process zero-copy)")
    ap.add_argument("--transports",
                    help="comma list, e.g. tcp,uds,loopback: run an "
                         "INTERLEAVED A/B across byte layers (ratios vs "
                         "the first) instead of a single run")
    ap.add_argument("--reps", type=int, default=3,
                    help="interleaved reps for --transports mode")
    ap.add_argument("--trace", action="store_true",
                    help="sample per-write traces in the leader and report "
                         "the slowest sampled write's span tree (per-phase "
                         "attribution: wal fsync vs follower-ack wait)")
    ap.add_argument("--out",
                    default="benchmarks/results/replication_3replica.json")
    args = ap.parse_args()

    tmp = tempfile.mkdtemp(prefix="repl3-bench-")
    try:
        config = {
            "shards": args.shards, "writer_threads": args.threads,
            "keys_per_shard_thread": args.keys,
            "value_bytes": args.value_bytes,
            "write_window": args.write_window,
        }
        if args.transports:
            names = [t.strip() for t in args.transports.split(",") if t.strip()]
            for t in names:
                if t not in TRANSPORTS:
                    ap.error(f"unknown transport {t!r} "
                             f"(expected {'|'.join(TRANSPORTS)})")
            ab = run_interleaved(
                [(t, (lambda t=t: run_once(args, t, trace=args.trace)))
                 for t in names],
                reps=args.reps, key="acked_writes_per_sec", log=log)
            result = {
                "bench": "replication_3replica_semisync_transport_ab",
                "timestamp": time.strftime(
                    "%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                "config": dict(config, transports=names,
                               topology="tcp/uds: 3 OS processes; "
                                        "loopback: colocated 1 process"),
                "ab": ab,
            }
            summary = {n: s.get("median") for n, s in
                       ab.get("summary", {}).items()}
            print(json.dumps({"acked_writes_per_sec_median": summary,
                              **{k: v for k, v in ab.items()
                                 if k.startswith("ratio_vs_")}}))
        else:
            results = run_once(args, args.transport, trace=args.trace)
            trace_lines = results.pop("slowest_write_trace", None)
            result = {
                "bench": "replication_3replica_semisync",
                "timestamp": time.strftime(
                    "%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                "config": dict(
                    config,
                    transport=args.transport,
                    topology=("leader + 2 followers colocated in ONE "
                              "process, in-process loopback transport, "
                              "replication mode 1 (semi-sync)"
                              if args.transport == "loopback" else
                              f"leader + 2 followers, 3 OS processes, "
                              f"{args.transport} loopback, replication "
                              f"mode 1 (semi-sync)"),
                ),
                "results": results,
            }
            if trace_lines:
                result["slowest_write_trace"] = trace_lines
            print(json.dumps(result["results"]))
        roof = host_roofline(tmp, args.value_bytes)
        raw_wps = roof["engine_writes_per_sec_no_replication"]
        result["host_roofline"] = roof
        if not args.transports:
            result["host_roofline"][
                "semisync_fraction_of_raw_engine"] = round(
                result["results"]["writes_per_sec"] / raw_wps, 3
            ) if raw_wps else None
        result["host_roofline"]["note"] = (
            "correctness-shaped bench on a small host: the absolute "
            "writes/s reads against the same-host raw-engine and fsync "
            "rates above, not against the reference's 32-core design "
            "point"
        )
        result["host_calibration"] = host_calibration(tmp)
        os.makedirs(os.path.dirname(args.out), exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)
        # a smoke gate, not just a recorder: acked loss or missed
        # convergence fails the run loudly (transport-bench-smoke
        # depends on this exit code)
        bad = []
        if args.transports:
            for name, ss in result["ab"].get("samples", {}).items():
                for s in ss:
                    if not isinstance(s, dict):
                        continue
                    if (s.get("acked_write_loss", 0)
                            or not s.get("both_followers_converged", True)):
                        bad.append(
                            f"{name}: loss={s.get('acked_write_loss')} "
                            f"converged="
                            f"{s.get('both_followers_converged')}")
        else:
            r = result["results"]
            if (r.get("acked_write_loss", 0)
                    or not r.get("both_followers_converged", True)):
                bad.append(
                    f"{args.transport}: loss={r.get('acked_write_loss')} "
                    f"converged={r.get('both_followers_converged')}")
        if bad:
            log("FAIL: " + "; ".join(bad))
            return 1
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
