#!/usr/bin/env python
"""BASELINE config #4-shaped benchmark: 3-replica semi-sync WAL tail.

Orchestrates three OS processes — one leader (replication mode 1:
every write acks only after a follower pulled it) and two followers
tailing the leader's WAL over the replication plane. Reports writes/s,
MB/s, follower convergence, and acked-write loss. (The config's
"Kafka WAL-tail" consumer role is the CDC observer path, covered by
tests/test_admin.py + tests/test_kafka.py; this bench measures the
3-replica semi-sync replication fabric itself.)

    python -m benchmarks.replication_3replica_bench \
        --shards 50 --keys 200 --value_bytes 1024

Reference harness shape: rocksdb_replicator/performance.cpp:57-207 (the
two-process original); config #4 in BASELINE.json adds the 3-replica +
WAL-tail consumer topology measured here.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import shutil
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def _spawn(role, port, db_dir, shards, keys, threads, value_bytes,
           upstream_port=0, mode=1, linger=60, trace=False,
           write_window=64, executor_threads=2):
    cmd = [
        sys.executable, "-m", "rocksplicator_tpu.replication.performance",
        "--role", role, "--port", str(port), "--db_dir", db_dir,
        "--num_shards", str(shards),
        "--num_write_threads", str(threads),
        "--num_keys_per_shard_thread", str(keys),
        "--value_size", str(value_bytes),
        "--replication_mode", str(mode),
        "--linger_sec", str(linger),
        "--write_window", str(write_window),
        # this bench targets small (2-4 core) CI hosts: a lean executor
        # avoids pure GIL thrash (serve is inline on the loop; executor
        # work is cold WAL scans and follower applies)
        "--executor_threads", str(executor_threads),
    ]
    if trace:
        cmd += ["--trace"]
    if upstream_port:
        cmd += ["--upstream_port", str(upstream_port)]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env,
    )


def host_roofline(tmp: str, value_bytes: int, n_writes: int = 2000) -> dict:
    """Same-host capability context (VERDICT r4 #5: the absolute
    writes/s is only interpretable against what THIS host can do).
    Measures (a) raw fsync rate — the floor under any durable ack —
    and (b) single-process engine write throughput with no replication,
    so the semi-sync number reads as a fraction of host capability
    rather than a bare absolute."""
    import tempfile as _tf

    from rocksplicator_tpu.storage.engine import DB, DBOptions

    # (a) fsync rate: append-and-fsync a small record repeatedly
    fd = os.open(os.path.join(tmp, "fsync_probe"),
                 os.O_CREAT | os.O_WRONLY | os.O_APPEND, 0o644)
    try:
        buf = b"x" * 4096
        n_fsync = 200
        t0 = time.monotonic()
        for _ in range(n_fsync):
            os.write(fd, buf)
            os.fsync(fd)
        fsync_per_sec = n_fsync / (time.monotonic() - t0)
    finally:
        os.close(fd)
    # (b) raw engine writes (no replication, async WAL)
    d = _tf.mkdtemp(dir=tmp)
    db = DB(os.path.join(d, "db"), DBOptions())
    val = b"v" * value_bytes
    t0 = time.monotonic()
    for i in range(n_writes):
        db.put(f"k{i:08d}".encode(), val)
    raw_elapsed = time.monotonic() - t0
    db.close()
    return {
        "fsync_per_sec": round(fsync_per_sec, 1),
        "engine_writes_per_sec_no_replication": round(
            n_writes / raw_elapsed, 1),
        "engine_mb_per_sec_no_replication": round(
            n_writes * value_bytes / raw_elapsed / 1e6, 2),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--shards", type=int, default=50)
    ap.add_argument("--keys", type=int, default=200)
    ap.add_argument("--threads", type=int, default=2)
    ap.add_argument("--value_bytes", type=int, default=1024)
    ap.add_argument("--write_window", type=int, default=64,
                    help="leader max in-flight (unacked) writes per shard; "
                         "1 = the old serial blocking write path")
    ap.add_argument("--leader_port", type=int, default=29391)
    ap.add_argument("--trace", action="store_true",
                    help="sample per-write traces in the leader and report "
                         "the slowest sampled write's span tree (per-phase "
                         "attribution: wal fsync vs follower-ack wait)")
    ap.add_argument("--out",
                    default="benchmarks/results/replication_3replica.json")
    args = ap.parse_args()

    tmp = tempfile.mkdtemp(prefix="repl3-bench-")
    followers = []
    leader = None
    try:
        f1 = _spawn("follower", args.leader_port + 1,
                    os.path.join(tmp, "f1"), args.shards, args.keys,
                    args.threads, args.value_bytes,
                    upstream_port=args.leader_port)
        f2 = _spawn("follower", args.leader_port + 2,
                    os.path.join(tmp, "f2"), args.shards, args.keys,
                    args.threads, args.value_bytes,
                    upstream_port=args.leader_port)
        followers = [f1, f2]
        time.sleep(2.0)
        t0 = time.monotonic()
        leader = _spawn("leader", args.leader_port,
                        os.path.join(tmp, "l"), args.shards, args.keys,
                        args.threads, args.value_bytes, linger=90,
                        trace=args.trace, write_window=args.write_window)
        # parse the leader's throughput line while it runs; with --trace
        # the slowest-write span tree is emitted (between markers) BEFORE
        # the throughput line, so this same loop captures it
        leader_line = None
        acked_line = None
        ack_window_line = None
        trace_lines = []
        in_trace = False
        for line in leader.stdout:
            log(f"[leader] {line.rstrip()}")
            if line.startswith("TRACE-SLOWEST-WRITE-BEGIN"):
                in_trace = True
            if in_trace:
                trace_lines.append(line.rstrip("\n"))
                if line.startswith("TRACE-SLOWEST-WRITE-END"):
                    in_trace = False
                continue
            m = re.search(
                r"TRACE-ACK-WINDOW sampled_ack_waits=(\d+) "
                r"max_overlapping=(\d+) max_window_depth=(\d+)", line)
            if m:
                ack_window_line = (int(m.group(1)), int(m.group(2)),
                                   int(m.group(3)))
                continue
            m = re.search(r"leader acked (\d+)/(\d+) writes", line)
            if m:
                acked_line = (int(m.group(1)), int(m.group(2)))
                continue
            m = re.search(r"wrote ~([\d.]+) MB in ([\d.]+)s", line)
            if m:
                leader_line = (float(m.group(1)), float(m.group(2)))
                break
        assert leader_line, "leader never reported its write phase"
        mb, elapsed = leader_line
        # expected total sequence per replica: each shard is written by
        # exactly one thread (stride tid, tid+T, ...), keys times
        total_writes = args.keys * args.shards
        # watch follower convergence via their periodic seq dumps
        want = total_writes
        deadline = time.monotonic() + 120
        seqs = {0: 0, 1: 0}
        while time.monotonic() < deadline and (
                seqs[0] < want or seqs[1] < want):
            for idx, f in enumerate(followers):
                line = f.stdout.readline()
                if line:
                    m = re.search(r"follower total seq: (\d+)", line)
                    if m:
                        seqs[idx] = int(m.group(1))
            time.sleep(0.1)
        converge_sec = time.monotonic() - t0
        result = {
            "bench": "replication_3replica_semisync",
            "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "config": {
                "topology": "leader + 2 followers, 3 OS processes, "
                            "TCP loopback, replication mode 1 (semi-sync)",
                "shards": args.shards, "writer_threads": args.threads,
                "keys_per_shard_thread": args.keys,
                "value_bytes": args.value_bytes,
                "write_window": args.write_window,
            },
            "results": {
                "writes_acked": acked_line[0] if acked_line else total_writes,
                "writes_total": total_writes,
                "leader_mb": mb,
                "leader_elapsed_s": elapsed,
                "writes_per_sec": round(total_writes / elapsed, 1),
                "acked_writes_per_sec": round(
                    (acked_line[0] if acked_line else total_writes)
                    / elapsed, 1),
                "write_window": args.write_window,
                "mb_per_sec": round(mb / elapsed, 2),
                "follower_seqs": [seqs[0], seqs[1]],
                "both_followers_converged": bool(
                    seqs[0] >= want and seqs[1] >= want),
                "convergence_sec_from_leader_start": round(converge_sec, 1),
                "acked_write_loss": max(0, want - min(seqs.values())),
            },
        }
        if ack_window_line:
            result["results"]["ack_window_trace"] = {
                "sampled_ack_waits": ack_window_line[0],
                "max_overlapping_ack_waits": ack_window_line[1],
                "max_window_depth": ack_window_line[2],
            }
        if args.trace and trace_lines:
            result["slowest_write_trace"] = trace_lines
        roof = host_roofline(tmp, args.value_bytes)
        raw_wps = roof["engine_writes_per_sec_no_replication"]
        result["host_roofline"] = roof
        result["host_roofline"]["semisync_fraction_of_raw_engine"] = round(
            result["results"]["writes_per_sec"] / raw_wps, 3
        ) if raw_wps else None
        result["host_roofline"]["note"] = (
            "correctness-shaped bench on a small host: the absolute "
            "writes/s reads against the same-host raw-engine and fsync "
            "rates above, not against the reference's 32-core design "
            "point"
        )
        os.makedirs(os.path.dirname(args.out), exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)
        print(json.dumps(result["results"]))
        return 0
    finally:
        for p in ([leader] if leader else []) + followers:
            try:
                p.terminate()
                p.wait(timeout=10)
            except Exception:
                pass
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
