"""Shared interleaved A/B harness for same-host benchmarks.

Rounds 7 and 9 learned the same lesson twice: this class of host (2-4
core CI box, shared disk) drifts by 2-5× hour to hour, so "before" and
"after" numbers measured in separate runs mostly measure the host, not
the change. The cure both benches hand-rolled is INTERLEAVING — run the
variants back to back inside each rep (A B C, A B C, ...) so drift and
fsync storms land on every variant equally, then compare medians across
reps. This module is that pattern as a library, plus the host
calibration block that makes an absolute number from one of these hosts
interpretable at all.

Usage::

    from benchmarks.ab_runner import host_calibration, run_interleaved

    out = run_interleaved(
        [("tcp", lambda: run_bench("tcp")),     # thunk -> float | dict
         ("uds", lambda: run_bench("uds"))],
        reps=3, key="acked_writes_per_sec")
    out["host_calibration"] = host_calibration(tmpdir)

``run_interleaved`` returns a JSON-ready dict: raw per-rep samples per
variant, per-variant median/best summaries, and ``ratio_vs_<baseline>``
computed median-to-median (the first variant is the baseline unless
``baseline=`` names another). No fake-zero fields: a variant whose thunk
raises is recorded as an error string, never as a 0.
"""

from __future__ import annotations

import os
import sys
import time
from statistics import median
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

Sample = Union[float, Dict[str, float]]


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def host_calibration(workdir: str, fsyncs: int = 100,
                     spin_ms: float = 80.0) -> Dict:
    """A small, fast probe of what THIS host can do right now — recorded
    next to every A/B so a reader (or a later round) can tell "the code
    got faster" from "the host had a good hour":

    - ``fsync_per_sec`` — the floor under any durable ack;
    - ``cpu_spin_score`` — single-thread Python ops/ms (GIL-bound
      orchestration scales with this);
    - ``loadavg_1m`` / ``cpu_count`` — ambient contention context.
    """
    fd = os.open(os.path.join(workdir, "ab_fsync_probe"),
                 os.O_CREAT | os.O_WRONLY | os.O_APPEND, 0o644)
    try:
        buf = b"x" * 4096
        t0 = time.perf_counter()
        for _ in range(fsyncs):
            os.write(fd, buf)
            os.fsync(fd)
        fsync_per_sec = fsyncs / (time.perf_counter() - t0)
    finally:
        os.close(fd)
        try:
            os.unlink(os.path.join(workdir, "ab_fsync_probe"))
        except OSError:
            pass
    n = 0
    deadline = time.perf_counter() + spin_ms / 1e3
    while time.perf_counter() < deadline:
        n += sum(range(100))  # fixed per-iteration work
    try:
        load1 = round(os.getloadavg()[0], 2)
    except OSError:
        load1 = None
    return {
        "fsync_per_sec": round(fsync_per_sec, 1),
        "cpu_spin_score": round(n / spin_ms / 1e3, 1),
        "loadavg_1m": load1,
        "cpu_count": os.cpu_count(),
    }


def _metric(sample: Sample, key: Optional[str]) -> Optional[float]:
    if isinstance(sample, dict):
        if key is None:
            raise ValueError(
                "dict samples need key= to pick the ratio metric")
        v = sample.get(key)
        return float(v) if v is not None else None
    return float(sample)


def run_interleaved(
    variants: Sequence[Tuple[str, Callable[[], Sample]]],
    reps: int = 3,
    key: Optional[str] = None,
    baseline: Optional[str] = None,
    higher_is_better: bool = True,
    log: Callable[[str], None] = _log,
) -> Dict:
    """Run every variant once per rep, in order, reps times; summarize.

    ``variants`` is an ordered sequence of (name, thunk); a thunk
    returns either a float or a dict of floats (then ``key`` names the
    metric ratios are computed over). The baseline for ratios is the
    first variant unless ``baseline`` names another.
    """
    names = [n for n, _ in variants]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate variant names: {names}")
    base = baseline if baseline is not None else names[0]
    if base not in names:
        raise ValueError(f"baseline {base!r} not in variants {names}")
    samples: Dict[str, List[Sample]] = {n: [] for n in names}
    errors: Dict[str, List[str]] = {n: [] for n in names}
    for rep in range(reps):
        for name, thunk in variants:
            t0 = time.perf_counter()
            try:
                sample = thunk()
            except Exception as e:  # recorded, never a fake zero
                errors[name].append(f"rep {rep}: {type(e).__name__}: {e}")
                log(f"ab[{rep + 1}/{reps}] {name}: ERROR {e}")
                continue
            samples[name].append(sample)
            m = _metric(sample, key)
            log(f"ab[{rep + 1}/{reps}] {name}: "
                + (f"{key}={m}" if key else f"{m}")
                + f" ({time.perf_counter() - t0:.1f}s)")
    summary: Dict[str, Dict] = {}
    for name in names:
        vals = [m for m in (_metric(s, key) for s in samples[name])
                if m is not None]
        if not vals:
            continue
        summary[name] = {
            "median": round(median(vals), 2),
            "best": round(max(vals) if higher_is_better else min(vals), 2),
            "all": [round(v, 2) for v in vals],
        }
    ratios: Dict[str, Optional[float]] = {}
    if base in summary and summary[base]["median"]:
        for name in names:
            if name == base or name not in summary:
                continue
            ratios[name] = round(
                summary[name]["median"] / summary[base]["median"], 2)
    return {
        "interleaved": True,
        "reps": reps,
        "order": names,
        "metric": key,
        "baseline": base,
        "samples": samples,
        "summary": summary,
        f"ratio_vs_{base}": ratios,
        "errors": {n: e for n, e in errors.items() if e},
    }


def sched_ab_failures(
    samples: Dict[str, List[Dict]],
    picks_of: Callable[[Dict], float],
    mismatch_label: str = "value mismatches",
) -> List[str]:
    """Shared pass/fail gates for a scheduler on/off A/B (compaction
    bench + macro-bench --sched_ab): every rep completed with a p99 and
    zero value mismatches, the sched_on arm actually picked, and the
    sched_off arm actually didn't. ``picks_of`` maps one rep sample to
    its compaction.sched_picks count (the two benches nest counters
    differently)."""
    failures: List[str] = []
    for mode in ("sched_on", "sched_off"):
        if not samples.get(mode):
            failures.append(f"no completed {mode} rep")
    for mode, reps_data in samples.items():
        for s in reps_data:
            if s["value_mismatches"]:
                failures.append(
                    f"{mode}: {s['value_mismatches']} {mismatch_label}")
            if s["get_p99_ms"] is None:
                failures.append(f"{mode}: no get p99 recorded")
    for s in samples.get("sched_on") or []:
        if picks_of(s) <= 0:
            failures.append("sched_on arm recorded zero sched picks")
    for s in samples.get("sched_off") or []:
        if picks_of(s) > 0:
            failures.append("sched_off arm recorded sched picks")
    return failures


def emit_gated_artifact(
    result: Dict,
    out_path: Optional[str],
    bench: str,
    log: Callable[[str], None] = _log,
) -> int:
    """Dump ``result`` (sorted, indented), write the artifact when
    ``out_path`` is set, print to stdout, and turn ``result['failures']``
    into the process exit code."""
    import json

    out_json = json.dumps(result, indent=2, sort_keys=True)
    if out_path:
        with open(out_path, "w") as f:
            f.write(out_json + "\n")
        log(f"{bench}: artifact -> {out_path}")
    print(out_json)
    failures = result.get("failures") or []
    if failures:
        for msg in failures:
            log(f"{bench}: FAILURE: {msg}")
        return 1
    return 0
