#!/usr/bin/env python
"""Streaming bounded-memory merge A/B: chunked k-way vs in-RAM compaction.

One large full compaction (overlapping sorted runs whose lane image is
several times the configured memory budget) timed through the round-17
streaming chunked merge (``storage/stream_merge.py``, fixed lane windows
per input run, carry-state across chunk boundaries) INTERLEAVED against
the round-9 in-RAM single pass on the SAME runs — the ab_runner pattern,
so host drift lands on both arms equally. Output equality is checksummed
file-for-file per rep.

The artifact's load-bearing numbers are the two peaks: the streamed
arm's ``peak_bytes_materialized`` must stay UNDER the budget while the
in-RAM arm's peak (and the input lane image) sit far OVER it — the proof
that the ceiling is enforced, not advisory. Loud failure gates: checksum
divergence, a streamed peak over budget, an in-RAM peak that never
exceeded the budget (the input was too small to prove anything), or a
stream that never crossed a chunk seam.

``make stream-merge-smoke`` runs the sub-minute configuration; tier-1
asserts the artifact shape (tests/test_stream_merge.py).
"""

from __future__ import annotations

import argparse
import hashlib
import os
import sys
import tempfile
import time
from typing import Dict, List

from benchmarks.ab_runner import (emit_gated_artifact, host_calibration,
                                  run_interleaved)


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _write_runs(root: str, keys: int, runs: int) -> List[str]:
    """Overlapping sorted runs: run r covers every r-th key at a later
    seq, so the merge sees dup-key stacks at every overlap."""
    import struct

    from rocksplicator_tpu.storage.sst import SSTWriter

    pack = struct.Struct("<q").pack
    paths = []
    for r in range(runs):
        path = os.path.join(root, f"run{r}.tsst")
        w = SSTWriter(path, 16 * 1024)
        step = r + 1
        for i in range(0, keys, step):
            w.add(b"k%09d" % i, (r + 1) * 1_000_000 + i, 1, pack(i * 7 + r))
        w.finish()
        paths.append(path)
    return paths


def _merge_arm(paths: List[str], root: str, tag: str, rep: int,
               mode: str, budget_bytes: int,
               target_file_bytes: int) -> Dict:
    import rocksplicator_tpu.storage.native_compaction as nc
    import rocksplicator_tpu.storage.stream_merge as sm
    from rocksplicator_tpu.storage.sst import SSTReader
    from rocksplicator_tpu.utils.stats import Stats

    out_dir = os.path.join(root, f"out-{tag}-{rep}")
    os.makedirs(out_dir, exist_ok=True)
    cnt = [0]

    def pf() -> str:
        cnt[0] += 1
        return os.path.join(out_dir, f"o{cnt[0]}.tsst")

    stats = Stats.get()
    chunks0 = stats.get_counter("compaction.stream_chunks")
    refills0 = stats.get_counter("compaction.stream_refills")
    sm.STREAM_MODE_OVERRIDE = mode
    tracker = sm.CompactionMemoryBudget.get().tracker()
    readers = [SSTReader(p) for p in paths]
    input_bytes = sum(os.path.getsize(p) for p in paths)
    try:
        t0 = time.monotonic()
        outs = nc.direct_merge_runs_to_files(
            readers, None, True, pf, 16 * 1024, 0, 10, target_file_bytes,
            mem_tracker=tracker, memory_budget_bytes=budget_bytes)
        secs = time.monotonic() - t0
    finally:
        sm.STREAM_MODE_OVERRIDE = None
        tracker.close()
        for r in readers:
            r.close()
    if outs is None:
        raise RuntimeError(f"{tag}: direct merge declined")
    h = hashlib.sha256()
    out_bytes = 0
    for p, _props in outs:
        with open(p, "rb") as f:
            h.update(f.read())
        out_bytes += os.path.getsize(p)
    for p, _props in outs:
        os.remove(p)
    return {
        "sec": round(secs, 3),
        "mb_per_sec": round(input_bytes / 1e6 / max(secs, 1e-9), 2),
        "peak_bytes_materialized": tracker.peak,
        "output_files": len(outs),
        "output_bytes": out_bytes,
        "output_sha256": h.hexdigest(),
        "stream_chunks": int(
            stats.get_counter("compaction.stream_chunks") - chunks0),
        "stream_refills": int(
            stats.get_counter("compaction.stream_refills") - refills0),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--keys", type=int, default=400000,
                   help="keyspace; run r holds every r-th key")
    p.add_argument("--runs", type=int, default=3)
    p.add_argument("--reps", type=int, default=3)
    p.add_argument("--budget_kb", type=int, default=2048,
                   help="compaction memory budget for the streamed arm "
                        "(lane image must be several times this)")
    p.add_argument("--target_file_kb", type=int, default=256,
                   help="output file split size; the streaming sink "
                        "buffers up to one file, so keep this well "
                        "under --budget_kb")
    p.add_argument("--chunk_entries", type=int, default=0,
                   help="override RSTPU_COMPACT_CHUNK_ENTRIES (0 = knob)")
    p.add_argument("--out", default=None)
    args = p.parse_args(argv)

    import rocksplicator_tpu.storage.stream_merge as sm

    budget = args.budget_kb * 1024
    sm.CompactionMemoryBudget.reset_for_test(budget)
    if args.chunk_entries:
        sm.CHUNK_ENTRIES_OVERRIDE = args.chunk_entries
    root = tempfile.mkdtemp(prefix="stream_merge_bench_")
    entries = sum(len(range(0, args.keys, r + 1))
                  for r in range(args.runs))
    log(f"stream_merge_bench: writing {args.runs} runs, "
        f"{entries} entries, budget {args.budget_kb} KiB")
    paths = _write_runs(root, args.keys, args.runs)
    input_bytes = sum(os.path.getsize(p) for p in paths)

    # untimed warmup on a tiny run: the first merge of a process pays
    # import + allocator first-touch costs that would land entirely on
    # whichever arm runs first (the ab_runner lesson, in miniature)
    warm_root = os.path.join(root, "warmup")
    os.makedirs(warm_root, exist_ok=True)
    warm_paths = _write_runs(warm_root, 4000, 2)
    for mode in ("never", "always"):
        _merge_arm(warm_paths, warm_root, f"w-{mode}", 0, mode, budget,
                   args.target_file_kb * 1024)

    def arm(mode: str, tag: str):
        rep_box = [0]

        def thunk() -> Dict:
            rep_box[0] += 1
            return _merge_arm(paths, root, tag, rep_box[0], mode, budget,
                              args.target_file_kb * 1024)
        return thunk

    ab = run_interleaved(
        [("in_ram", arm("never", "ram")),
         ("streamed", arm("always", "str"))],
        reps=args.reps, key="mb_per_sec", log=log)
    ab["host_calibration"] = host_calibration(root)

    failures: List[str] = []
    ram_reps = [s for s in ab["samples"].get("in_ram", [])
                if isinstance(s, dict)]
    str_reps = [s for s in ab["samples"].get("streamed", [])
                if isinstance(s, dict)]
    if len(ram_reps) < args.reps or len(str_reps) < args.reps:
        failures.append("an arm failed to complete every rep")
    for a, b in zip(ram_reps, str_reps):
        if a["output_sha256"] != b["output_sha256"]:
            failures.append("streamed output diverged from in-RAM "
                            "(checksum mismatch)")
    for s in str_reps:
        if s["peak_bytes_materialized"] > budget:
            failures.append(
                f"streamed peak {s['peak_bytes_materialized']} "
                f"exceeded the {budget}-byte budget")
        if s["stream_chunks"] < 2:
            failures.append("streamed arm never crossed a chunk seam")
    for s in ram_reps:
        if s["peak_bytes_materialized"] <= budget:
            failures.append(
                "in-RAM peak never exceeded the budget — input too "
                "small to prove the ceiling; raise --keys")
        if s["stream_chunks"]:
            failures.append("in_ram arm streamed")

    result = {
        "bench": "stream_merge_bench",
        "entries": entries,
        "runs": args.runs,
        "input_bytes": input_bytes,
        "budget_bytes": budget,
        "chunk_entries": (args.chunk_entries
                          or sm.default_chunk_entries()),
        "ab": ab,
        "failures": failures,
    }
    rc = emit_gated_artifact(result, args.out, "stream_merge_bench",
                             log=log)
    sm.CompactionMemoryBudget.reset_for_test()
    sm.CHUNK_ENTRIES_OVERRIDE = None
    import shutil
    shutil.rmtree(root, ignore_errors=True)
    return rc


if __name__ == "__main__":
    sys.exit(main())
