"""Flush / host-compaction / block-cache microbench (round 9).

Same-host interleaved A/B for the three engine paths ISSUE 5 vectorized:

- **flush**: the array drain→lexsort→planar pipeline
  (MemTable.drain_lanes + engine._try_array_flush) vs the SEED flush
  algorithm (sorted(mem) entry tuples + per-entry pack_entries repack +
  planar sink without bulk bloom) reproduced here verbatim as the
  "before" side. Interleaved best-of-N on the identical memtable;
  read-back parity is asserted, not assumed.
- **compact**: CPU full compaction over all-planar inputs through the
  direct array sink (CpuCompactionBackend.merge_runs_to_files) vs the
  same backend with the sink disabled (the seed's heap-merge +
  per-entry _write_entry_stream path). Output parity asserted via full
  iteration.
- **block cache**: repeated point gets over a flushed+compacted DB with
  the decoded-block cache disabled vs enabled; hit/miss come from the
  /stats counters, not inference.

Emits ONE JSON file (no fake-zero fields — every number is measured in
this run): flush_mb_per_sec, compact_mb_per_sec, block_cache_hit_rate
plus the before-sides and speedups.

Run directly or via ``python bench.py --flush_bench`` /
``make flush-bench-smoke``.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time
from typing import List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from rocksplicator_tpu.storage import DB, DBOptions  # noqa: E402
from rocksplicator_tpu.storage.compaction import CpuCompactionBackend  # noqa: E402
from rocksplicator_tpu.storage.memtable import MemTable  # noqa: E402
from rocksplicator_tpu.storage.merge import UInt64AddOperator  # noqa: E402
from rocksplicator_tpu.storage.records import OpType  # noqa: E402
from rocksplicator_tpu.storage.sst import BlockCache, SSTReader  # noqa: E402
from rocksplicator_tpu.utils.stats import Stats  # noqa: E402


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _fill_memtable(mem: MemTable, keys: int, val_bytes: int) -> int:
    """Mixed PUT/MERGE/DELETE uniform-width workload; returns payload
    bytes (keys + values of live entries — the PERF.md convention)."""
    payload = 0
    for i in range(keys):
        k = f"key{i:013d}".encode()
        m = i % 10
        if m == 0:
            mem.apply(k, i + 1, OpType.DELETE, b"")
            payload += len(k)
        elif m == 1:
            v = (i).to_bytes(8, "little").ljust(val_bytes, b"\x00")
            mem.apply(k, i + 1, OpType.MERGE, v)
            payload += len(k) + len(v)
        else:
            v = (i).to_bytes(8, "little").ljust(val_bytes, b"\x00")
            mem.apply(k, i + 1, OpType.PUT, v)
            payload += len(k) + len(v)
    return payload


def _seed_flush(path: str, mem: MemTable, block_bytes: int = 32 * 1024,
                bits_per_key: int = 10) -> bool:
    """The SEED's flush algorithm (pre-round-9 engine._write_mem_sst +
    _try_planar_flush), reproduced as the A/B "before" side: pure-Python
    sorted entry stream, per-entry width scan, per-entry pack_entries
    repack, planar sink building its bloom from a per-key Python loop."""
    from rocksplicator_tpu.ops.kv_format import UnsupportedBatch, pack_entries
    from rocksplicator_tpu.tpu.format import (planar_stride, planar_widths,
                                              write_sst_from_arrays)

    entries = list(mem.entries())
    if not entries:
        return False
    klen0 = len(entries[0][0])
    vlen0 = None
    for key, _seq, vtype, value in entries:
        if len(key) != klen0 or len(key) > 24:
            return False
        if int(vtype) == 2:
            if value:
                return False
        elif vlen0 is None:
            vlen0 = len(value)
        elif len(value) != vlen0:
            return False
    try:
        batch = pack_entries(
            entries, val_bytes=max(4, ((vlen0 or 0) + 3) // 4 * 4))
    except UnsupportedBatch:
        return False
    n = len(entries)
    arrays = {
        f: getattr(batch, f)[:n]
        for f in ("key_words_be", "key_words_le", "key_len", "seq_hi",
                  "seq_lo", "vtype", "val_words", "val_len")
    }
    widths = planar_widths(arrays, n)
    if widths is None:
        return False
    stride = planar_stride(*widths)
    props = write_sst_from_arrays(
        arrays, n, path, block_entries=max(64, block_bytes // stride),
        planar=True, bits_per_key=bits_per_key,
    )
    return props is not None


def bench_flush(workdir: str, keys: int, val_bytes: int, reps: int) -> dict:
    mem = MemTable()
    payload = _fill_memtable(mem, keys, val_bytes)
    db = DB(os.path.join(workdir, "flushdb"),
            DBOptions(memtable_bytes=1 << 30,
                      disable_auto_compaction=True))
    after: List[float] = []
    before: List[float] = []
    for r in range(reps):
        path_a = os.path.join(workdir, f"new{r}.tsst")
        t0 = time.perf_counter()
        db._write_mem_sst(path_a, mem)
        after.append(time.perf_counter() - t0)
        path_b = os.path.join(workdir, f"old{r}.tsst")
        t0 = time.perf_counter()
        ok = _seed_flush(path_b, mem)
        before.append(time.perf_counter() - t0)
        assert ok, "seed flush path declined a uniform workload"
    # read-back parity — the A/B is void if the sinks disagree
    got_a = list(SSTReader(os.path.join(workdir, "new0.tsst")).iterate())
    got_b = list(SSTReader(os.path.join(workdir, "old0.tsst")).iterate())
    assert got_a == got_b and len(got_a) == keys, (
        f"flush parity broken: {len(got_a)} vs {len(got_b)} entries")
    db.close()
    mb = payload / 1e6
    res = {
        "flush_entries": keys,
        "flush_payload_mb": round(mb, 3),
        "flush_sec_all": [round(x, 4) for x in after],
        "flush_before_sec_all": [round(x, 4) for x in before],
        "flush_mb_per_sec": round(mb / min(after), 2),
        "flush_before_mb_per_sec": round(mb / min(before), 2),
        "flush_speedup": round(min(before) / min(after), 2),
    }
    log(f"flush: {res['flush_mb_per_sec']} MB/s vs seed "
        f"{res['flush_before_mb_per_sec']} MB/s "
        f"({res['flush_speedup']}x)")
    return res


def _build_compact_db(path: str, backend, keys: int, runs: int,
                      val_bytes: int) -> tuple:
    opts = DBOptions(memtable_bytes=1 << 30, compaction_backend=backend,
                     merge_operator=UInt64AddOperator(),
                     disable_auto_compaction=True)
    db = DB(path, opts)
    one = (1).to_bytes(8, "little").ljust(val_bytes, b"\x00")
    payload = 0
    for r in range(runs):
        for i in range(keys):
            k = f"key{(i * 13 + r) % (keys * 2):013d}".encode()
            m = (i + r) % 5
            if m == 0:
                db.merge(k, one)
                payload += len(k) + len(one)
            elif m == 1:
                db.delete(k)
                payload += len(k)
            else:
                v = (i).to_bytes(8, "little").ljust(val_bytes, b"\x00")
                db.put(k, v)
                payload += len(k) + len(v)
        db.flush()
    return db, payload


def bench_compact(workdir: str, keys: int, runs: int,
                  val_bytes: int) -> dict:
    # AFTER: the cpu backend's direct array sink (all inputs planar —
    # flush now writes planar files)
    db_a, payload = _build_compact_db(
        os.path.join(workdir, "compact_after"), CpuCompactionBackend(),
        keys, runs, val_bytes)
    t0 = time.perf_counter()
    db_a.compact_range()
    t_after = time.perf_counter() - t0
    out_a = list(db_a.new_iterator())
    db_a.close()
    # BEFORE: same backend, direct sink disabled → the seed's tuple path
    # (heap merge + per-entry SSTWriter.add loop)
    be = CpuCompactionBackend()
    be.merge_runs_to_files = None
    db_b, _ = _build_compact_db(
        os.path.join(workdir, "compact_before"), be, keys, runs, val_bytes)
    t0 = time.perf_counter()
    db_b.compact_range()
    t_before = time.perf_counter() - t0
    out_b = list(db_b.new_iterator())
    db_b.close()
    assert out_a == out_b and out_a, (
        f"compaction parity broken: {len(out_a)} vs {len(out_b)} rows")
    mb = payload / 1e6
    res = {
        "compact_input_entries": keys * runs,
        "compact_payload_mb": round(mb, 3),
        "compact_sec": round(t_after, 4),
        "compact_before_sec": round(t_before, 4),
        "compact_mb_per_sec": round(mb / t_after, 2),
        "compact_before_mb_per_sec": round(mb / t_before, 2),
        "compact_speedup": round(t_before / t_after, 2),
    }
    log(f"compact: {res['compact_mb_per_sec']} MB/s vs tuple path "
        f"{res['compact_before_mb_per_sec']} MB/s "
        f"({res['compact_speedup']}x)")
    return res


def bench_block_cache(workdir: str, keys: int, gets: int) -> dict:
    path = os.path.join(workdir, "cachedb")
    opts = DBOptions(memtable_bytes=1 << 30,
                     disable_auto_compaction=True)
    db = DB(path, opts)
    for i in range(keys):
        db.put(f"key{i:013d}".encode(),
               (i).to_bytes(8, "little"))
    db.flush()
    probe = [f"key{(i * 7919) % keys:013d}".encode() for i in range(gets)]

    def run_gets() -> float:
        t0 = time.perf_counter()
        for k in probe:
            db.get(k)
        return time.perf_counter() - t0

    # cold pass (disabled cache) — the "before" side
    BlockCache.reset_for_test(capacity=0)
    t_off = run_gets()
    # enabled cache: first pass fills, second pass measures the hit path
    BlockCache.reset_for_test(capacity=64 << 20)
    Stats.reset_for_test()
    run_gets()
    t_on = run_gets()
    stats = Stats.get()
    hits = stats.get_counter("storage.block_cache.hit")
    misses = stats.get_counter("storage.block_cache.miss")
    db.close()
    BlockCache.reset_for_test()  # back to env-configured default
    assert hits > 0, "block cache never hit — counters dead?"
    res = {
        "block_cache_gets": gets,
        "block_cache_get_per_sec": round(gets / t_on, 1),
        "block_cache_get_per_sec_disabled": round(gets / t_off, 1),
        "block_cache_hits": int(hits),
        "block_cache_misses": int(misses),
        "block_cache_hit_rate": round(hits / max(1, hits + misses), 4),
        "block_cache_get_speedup": round(t_off / t_on, 2),
    }
    log(f"block cache: {res['block_cache_get_per_sec']}/s hot vs "
        f"{res['block_cache_get_per_sec_disabled']}/s disabled, "
        f"hit rate {res['block_cache_hit_rate']}")
    return res


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--keys", type=int, default=200_000,
                    help="entries per flush memtable (PERF methodology: "
                         "200k uniform-width)")
    ap.add_argument("--val_bytes", type=int, default=8)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--compact_keys", type=int, default=None,
                    help="keys per compaction input run "
                         "(default: --keys/4)")
    ap.add_argument("--compact_runs", type=int, default=4)
    ap.add_argument("--cache_gets", type=int, default=20_000)
    ap.add_argument("--out", default=None,
                    help="JSON output path (default: "
                         "benchmarks/results/flush_bench.json)")
    args = ap.parse_args(argv)

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = args.out or os.path.join(repo, "benchmarks", "results",
                                   "flush_bench.json")
    workdir = tempfile.mkdtemp(prefix="flush_bench_")
    result = {
        "bench": "flush_compact_blockcache",
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "host_cores": len(os.sched_getaffinity(0)),
    }
    try:
        result.update(bench_flush(
            workdir, args.keys, args.val_bytes, args.reps))
        result.update(bench_compact(
            workdir, args.compact_keys or max(1000, args.keys // 4),
            args.compact_runs, args.val_bytes))
        result.update(bench_block_cache(
            workdir, max(1000, args.keys // 4), args.cache_gets))
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        json.dump(result, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps(result, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
