#!/usr/bin/env python
"""Headline benchmark: shard-batched TPU compaction throughput vs CPU.

Models BASELINE config ladder steps 1-3 in miniature: S shards of counter
workload (PUT/MERGE/DELETE mix) run the fused merge-resolve + bloom
pipeline. The TPU number is the vmapped single-launch pipeline; the CPU
baseline ladder is:

  1. single-core vectorized numpy (lexsort+reduceat, native-C bloom);
  2. the same, multiprocess over shards on every available core;
  3. a 32-core extrapolation: single-core GB/s x 32 (perfect scaling —
     flattering to the CPU, so ``vs_baseline`` is a lower bound). This is
     the mandated BASELINE.json comparator ("≥5x vs 32-core CPU"); on
     hosts with 32+ cores the measured multiprocess number is used
     directly.

Prints exactly ONE JSON line on stdout:
    {"metric": ..., "value": N, "unit": "GB/s", "vs_baseline": N, ...}
Diagnostics go to stderr.

``value`` is the framework's best measured compaction throughput on the
available hardware: the TPU kernel when a chip was granted, else the
framework's production CPU fallback (the native C merge-resolve + bulk
bloom when storage/native is loaded, the numpy backend otherwise —
the same dispatch NumpyCompactionBackend/TpuCompactionBackend use).
``value_source`` names the path; ``degraded_no_accelerator: true`` +
``tpu_kernel_gbps`` keep a degraded run and its raw kernel-emulation
number distinguishable.
"""

import json
import multiprocessing
import os
import queue as queue_mod
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np


def log(msg):
    print(msg, file=sys.stderr, flush=True)


# CPU baselines run at BASE shards; the TPU phase then CLIMBS the batch
# size (8 -> 16 -> 32 shards) to amortize the ~23 ms per-dispatch floor,
# keeping the best completed number. Each climb step costs a fresh XLA
# compile, which on the shared pool can take minutes — so the climb stops
# once BENCH_TIME_BUDGET is spent, and an atexit hook prints the
# best-so-far JSON even if the driver's timeout TERMs a hung attempt.
SHARDS = int(os.environ.get("BENCH_SHARDS", "8"))
CLIMB_SHARDS = tuple(
    int(s) for s in os.environ.get("BENCH_CLIMB", "8,16,32").split(",") if s
)
TIME_BUDGET = float(os.environ.get("BENCH_TIME_BUDGET", "420"))
ENTRIES = int(os.environ.get("BENCH_ENTRIES", str(1 << 17)))
ITERS = int(os.environ.get("BENCH_ITERS", "10"))
KEY_BYTES = 16
VAL_BYTES = 8
# what a CPU compaction would read per entry in the SST encoding:
# u32 klen + key + u64 seq + u8 vtype + u32 vlen + value
ENTRY_BYTES = 4 + KEY_BYTES + 8 + 1 + 4 + VAL_BYTES
TOTAL_BYTES = SHARDS * ENTRIES * ENTRY_BYTES
BASELINE_CORES = 32  # the BASELINE.json comparator


def build_inputs():
    from rocksplicator_tpu.models.compaction_model import synth_counter_batch

    shards = []
    for s in range(SHARDS):
        shards.append(synth_counter_batch(
            ENTRIES, key_space=ENTRIES // 8, seed=1234 + s,
            key_bytes=KEY_BYTES,
        ))
    stacked = {
        k: np.stack([b[k] for b in shards]) for k in shards[0]
    }
    return stacked


def _tpu_worker_main(cmd_q, res_q):
    """Persistent TPU worker child (module-level: spawn must pickle it).

    Initializes jax ONCE — reported as a readiness message so the parent's
    init watchdog and the phase runner are the SAME process — then serves
    phase commands off a queue. Rounds 1-3 paid full jax init (the thing
    that times out on the shared pool) per phase in throwaway children;
    the warmed runtime and in-process XLA cache now serve every phase and
    every climb step. A persistent on-disk compilation cache additionally
    survives bench re-runs on the same host."""
    # The parent's stdout is the driver-facing JSON pipe. This child
    # inherits it across spawn; if the parent is TERM'd (os._exit skips
    # the multiprocessing atexit reaper) a still-running worker would
    # hold the pipe open and the driver's read would never see EOF.
    # Redirect this process's stdout into stderr so ONLY the parent
    # holds the JSON pipe.
    try:
        os.dup2(sys.stderr.fileno(), sys.stdout.fileno())
    except OSError:
        pass
    # test seam: simulate a slow pool-side init (the parent pops the env
    # after the FIRST spawn so the CPU-fallback worker starts promptly)
    fake_delay = float(os.environ.get("BENCH_WORKER_INIT_DELAY", "0") or 0)
    if fake_delay > 0:
        time.sleep(fake_delay)
    try:
        if os.environ.get("JAX_PLATFORMS") == "cpu":
            import __graft_entry__ as graft

            graft._honor_platform_env()
        import jax

        try:
            jax.config.update(
                "jax_compilation_cache_dir",
                os.environ.get("BENCH_XLA_CACHE", "/tmp/rstpu_xla_cache"),
            )
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 1.0)
        except Exception as e:  # older jax: knobs absent — cache is a bonus
            log(f"worker: no persistent compile cache ({e!r})")
        t0 = time.monotonic()
        jax.devices()
        res_q.put({"ok": True, "ready": True,
                   "backend": jax.default_backend(),
                   "init_sec": round(time.monotonic() - t0, 1)})
    except Exception as e:
        res_q.put({"ok": False, "ready": True, "err": repr(e)})
        return
    while True:
        cmd = cmd_q.get()
        if not cmd or cmd.get("phase") == "quit":
            return
        try:
            if cmd["phase"] == "kernel":
                g = bench_tpu_kernel(cmd["shards"], cmd.get("sort_backend"))
            else:
                g = bench_tpu_transfer(build_inputs(), cmd["kernel_gbps"])
            res_q.put({"ok": True, "gbps": g,
                       "backend": jax.default_backend()})
        except Exception as e:  # noqa: BLE001 — child reports, parent decides
            res_q.put({"ok": False, "err": repr(e)})


class _TpuWorker:
    """Parent-side handle. The parent NEVER initializes jax itself: a
    pool-side XLA compile can hang for minutes inside one C call and
    CPython delivers signals only between bytecodes — a parent compiling
    inline could never run its SIGTERM best-so-far emitter. All waits
    happen in 1s queue slices (signal-interruptible)."""

    def __init__(self):
        ctx = multiprocessing.get_context("spawn")
        self.cmd_q = ctx.Queue()
        self.res_q = ctx.Queue()
        self.proc = ctx.Process(
            target=_tpu_worker_main, args=(self.cmd_q, self.res_q),
            daemon=True,
        )
        self.proc.start()

    def _wait_result(self, timeout_sec: float):
        """Result dict, {"ok": False, err} if the worker died, or None on
        timeout (caller decides whether to abandon)."""
        deadline = time.monotonic() + timeout_sec
        while time.monotonic() < deadline:
            try:
                return self.res_q.get(timeout=1.0)
            except queue_mod.Empty:
                if not self.proc.is_alive():
                    return {"ok": False, "err": "worker process died"}
        return None

    def wait_ready(self, timeout_sec: float):
        return self._wait_result(timeout_sec)

    def run_phase(self, phase: str, shards: int, timeout_sec: float,
                  kernel_gbps: float = 0.0, sort_backend: str = None):
        self.cmd_q.put(
            {"phase": phase, "shards": shards, "kernel_gbps": kernel_gbps,
             "sort_backend": sort_backend})
        return self._wait_result(timeout_sec)

    _abandoned = []  # see _finish(): reaped with TERM at exit

    def abandon(self):
        """Walk away from a hung worker WITHOUT killing it: SIGKILLing a
        process holding a live tunnel session wedges the grant pool-side
        (round-1 postmortem), and multiprocessing's atexit handler TERMs
        any still-registered daemon child — so deregister it and let it
        finish (or hang) on its own until exit time, when _finish sends
        one TERM (safe per the tunnel discipline — only KILL wedges) and
        reaps it."""
        log(f"abandoning tpu worker pid={self.proc.pid} "
            f"(not killed: SIGKILL wedges the tunnel grant)")
        # capture the handles NOW: the phase-timeout path nulls
        # worker.proc after abandoning, and _finish must still be able
        # to TERM/join/close this worker
        _TpuWorker._abandoned.append((self.proc, self.cmd_q, self.res_q))
        try:
            _registered_children().discard(self.proc)
        except Exception as e:
            log(f"worker deregistration failed (harmless): {e!r}")

    def quit(self):
        try:
            self.cmd_q.put({"phase": "quit"})
            # flush the feeder thread NOW: callers may os._exit right
            # after (see _finish), which would drop a buffered quit and
            # leave the worker parked on cmd_q.get() forever
            self.cmd_q.close()
            self.cmd_q.join_thread()
        except Exception:
            pass

    def reap(self, timeout: float = 5.0) -> bool:
        """TERM (never KILL — only SIGKILL wedges a tunnel grant), join,
        release the queues, and drop this worker from the exit-time
        _abandoned list. Returns True when the process is gone. The
        salvage path calls this so a degraded run's process table is
        clean when the JSON is emitted, not only at interpreter exit
        (VERDICT item 6b)."""
        proc = self.proc
        if proc is None:
            return True
        try:
            if proc.is_alive():
                proc.terminate()
        except Exception as e:
            log(f"reap: TERM failed: {e!r}")
        try:
            proc.join(timeout)
        except Exception as e:
            log(f"reap: join failed: {e!r}")
        try:
            alive = proc.is_alive()
        except Exception:
            alive = True
        if alive:
            log(f"reap: worker pid={proc.pid} ignored TERM; leaving to "
                f"the exit reaper")
            return False
        for q in (self.cmd_q, self.res_q):
            try:
                q.close()
                q.join_thread()
            except Exception:
                pass
        _TpuWorker._abandoned = [
            t for t in _TpuWorker._abandoned if t[0] is not proc
        ]
        self.proc = None
        return True


def _registered_children():
    """The multiprocessing registry of still-REGISTERED children (the
    private CPython set both abandon() and the TERM reaper consult —
    keep the introspection in one place)."""
    import multiprocessing.process as _mpp

    return getattr(_mpp, "_children", None) or set()


def _model_args(dev):
    # (key_words_le is not shipped: the kernel byteswap-derives LE lanes)
    return (
        dev["key_words_be"], dev["key_len"],
        dev["seq_hi"], dev["seq_lo"], dev["vtype"], dev["val_words"],
        dev["val_len"], dev["valid"],
    )


def _env_sort_backend() -> str:
    # BENCH_PALLAS_SORT=1 swaps in the VMEM-resident bitonic sort;
    # =2 the fully-fused sort+resolve kernel (ops/pallas_resolve.py).
    level = os.environ.get("BENCH_PALLAS_SORT", "0")
    backends = {"0": "lax", "1": "pallas", "2": "pallas_fused"}
    if level not in backends:
        log(f"BENCH_PALLAS_SORT={level!r} is not one of 0/1/2 — "
            f"using the lax backend")
    return backends.get(level, "lax")


def _make_model(sort_backend: str = None):
    from rocksplicator_tpu.models import CompactionModel

    # 16-byte keys + 32-bit seqs: reduced-key sort (_sort_merge_order);
    # emit_planar adds on-device SST block encoding (plane words +
    # checksums — the production sink format) to the measured pipeline.
    return CompactionModel(
        capacity=ENTRIES, uniform_klen=True, seq32=True,
        key_words=KEY_BYTES // 4, emit_planar=True,
        row_klen=KEY_BYTES, row_vlen=VAL_BYTES,
        sort_backend=sort_backend or _env_sort_backend(),
    )


def bench_tpu_kernel(shards, sort_backend: str = None) -> float:
    """Kernel-only GB/s at one batch size. Inputs are GENERATED ON
    DEVICE (same distribution as the host generator, jax PRNG): the
    tunnel moves ~30 MB/s, so shipping a 32-shard batch (222 MB of
    lanes) would take minutes and says nothing about the kernel.
    Host↔device costs are measured by bench_tpu_transfer."""
    import jax
    import jax.numpy as jnp

    from rocksplicator_tpu.models.compaction_model import (
        synth_counter_batch_jax)

    total_bytes = shards * ENTRIES * ENTRY_BYTES
    model = _make_model(sort_backend)
    fwd = jax.jit(jax.vmap(model.forward))

    def gen_all():
        batches = [
            synth_counter_batch_jax(
                ENTRIES, key_space=ENTRIES // 8, seed=1234 + s,
                key_bytes=KEY_BYTES)
            for s in range(shards)
        ]
        return {
            k: jnp.stack([b[k] for b in batches])
            for k in batches[0]
        }

    t0 = time.monotonic()
    dev = jax.jit(gen_all)()
    jax.block_until_ready(dev)
    log(f"on-device input gen dispatched: {time.monotonic() - t0:.1f}s "
        f"({shards} shards x {ENTRIES})")
    args = _model_args(dev)
    t0 = time.monotonic()
    out = fwd(*args)
    jax.block_until_ready(out)
    # NOTE: this small D2H readback is load-bearing on the tunneled
    # (axon) platform: block_until_ready does NOT drain the launch queue
    # there, but a readback does — and flips the session into synchronous
    # dispatch, making the timed loop below honest per-iteration time.
    log(f"tpu compile+first run: {time.monotonic() - t0:.1f}s, "
        f"counts={np.asarray(out['count'])[:4]}...")
    # steady state, resident inputs
    t0 = time.monotonic()
    for _ in range(ITERS):
        out = fwd(*args)
    jax.block_until_ready(out)
    dt = (time.monotonic() - t0) / ITERS
    gbps = total_bytes / dt / 1e9
    log(f"tpu kernel [{shards} shards]: {dt * 1e3:.1f} ms/iter over "
        f"{total_bytes / 1e6:.0f} MB => {gbps:.2f} GB/s")
    return gbps


def bench_tpu_transfer(stacked, kernel_gbps: float) -> float:
    """Transfer-inclusive GB/s: per-shard slices stream H2D
    double-buffered while the previous slice's kernel runs (device_put
    and dispatch are async — block only at the end of the pipeline).
    Runs at 8 shards: this phase measures host→device streaming, which
    the tunnel bandwidth bounds regardless of batch size."""
    import jax
    import jax.numpy as jnp

    xfer_shards = min(len(stacked["key_len"]), 8)
    model = _make_model()
    fwd1 = jax.jit(model.forward)  # per-shard launch for the pipeline
    host_shards = [
        {k: np.ascontiguousarray(v[s]) for k, v in stacked.items()}
        for s in range(xfer_shards)
    ]
    # warm up the per-shard compile outside the timed loop
    w = {k: jnp.asarray(v) for k, v in host_shards[0].items()}
    jax.block_until_ready(fwd1(*_model_args(w)))
    reps = max(1, ITERS // 3)
    xfer_bytes = xfer_shards * ENTRIES * ENTRY_BYTES
    t0 = time.monotonic()
    for _ in range(reps):
        outs = []
        nxt = {k: jax.device_put(v) for k, v in host_shards[0].items()}
        for s in range(xfer_shards):
            cur = nxt
            if s + 1 < xfer_shards:  # prefetch next shard while this runs
                nxt = {k: jax.device_put(v)
                       for k, v in host_shards[s + 1].items()}
            outs.append(fwd1(*_model_args(cur)))
        jax.block_until_ready(outs)
    dt_x = (time.monotonic() - t0) / reps
    gbps_x = xfer_bytes / dt_x / 1e9
    log(f"tpu transfer-inclusive (double-buffered, {xfer_shards} shards): "
        f"{dt_x * 1e3:.1f} ms/iter => {gbps_x:.2f} GB/s  "
        f"({kernel_gbps / gbps_x:.1f}x slower than kernel-only per byte)")
    return gbps_x


def _shard_batch(stacked, s):
    from rocksplicator_tpu.ops.kv_format import KVBatch

    return KVBatch(
        key_words_be=stacked["key_words_be"][s],
        key_words_le=stacked["key_words_le"][s],
        key_len=stacked["key_len"][s],
        seq_hi=stacked["seq_hi"][s],
        seq_lo=stacked["seq_lo"][s],
        vtype=stacked["vtype"][s],
        val_words=stacked["val_words"][s],
        val_len=stacked["val_len"][s],
        valid=stacked["valid"][s],
        val_bytes=VAL_BYTES,
    )


def _cpu_one_shard(stacked, s) -> int:
    """Single shard: merge-resolve + bloom build (the same job the TPU
    pipeline does), best available CPU implementation — the native C
    merge-resolve + bulk bloom when the library is loaded (this IS the
    production fallback path: NumpyCompactionBackend dispatches through
    cpu_merge_resolve), else the numpy implementations."""
    from rocksplicator_tpu.storage.bloom import BloomFilter
    from rocksplicator_tpu.tpu.backend import cpu_merge_resolve

    arrays, count = cpu_merge_resolve(
        _shard_batch(stacked, s), uint64_add=True, drop_tombstones=True
    )
    kw = arrays[0]
    kl = arrays[1]
    kb = (
        np.ascontiguousarray(kw.astype(">u4"))
        .view(np.uint8).reshape(len(kw), 24)
    )
    BloomFilter.build_from_arrays(kb[:count], kl[:count])
    return count


def _cpu_backend_name() -> str:
    from rocksplicator_tpu.storage.native.binding import get_native

    lib = get_native()
    if lib is not None and getattr(lib, "has_merge_resolve", False):
        return "native_backend"
    return "numpy_backend"


# The pool workers read the dataset through this module global, set
# before fork: map() then ships only shard indices, not the data.
_MP_STACKED = None


def _mp_shard(s):
    return _cpu_one_shard(_MP_STACKED, s)


def bench_numpy_single(stacked):
    t0 = time.monotonic()
    total = 0
    for s in range(SHARDS):
        total += _cpu_one_shard(stacked, s)
    dt = time.monotonic() - t0
    gbps = TOTAL_BYTES / dt / 1e9
    log(f"cpu single-core ({_cpu_backend_name()}): {dt * 1e3:.0f} ms/pass (out={total}) "
        f"=> {gbps:.3f} GB/s")
    return gbps


def bench_numpy_multiproc(stacked):
    """Multiprocess over shards on every available core — the honest
    measured CPU parallel number on THIS host. Returns
    (gbps_or_None, cores_available, workers_used). MUST run before any
    jax device init in this process: fork inherits the dataset via
    _MP_STACKED, and forking a live multithreaded runtime is
    deadlock-prone."""
    global _MP_STACKED
    cores = len(os.sched_getaffinity(0))
    # BENCH_MP_WORKERS forces the worker count (test seam + operator
    # override); default remains one worker per available core
    forced = int(os.environ.get("BENCH_MP_WORKERS", "0") or 0)
    workers = forced if forced > 0 else min(cores, SHARDS)
    if workers <= 1:
        log("cpu multiprocess: 1 core available — same as single-core")
        return None, cores, 1
    if cores > SHARDS:
        log(f"cpu multiprocess: host has {cores} cores but only {SHARDS} "
            f"shards — raise BENCH_SHARDS to use them all")
    _MP_STACKED = stacked
    try:
        ctx = multiprocessing.get_context("fork")
        with ctx.Pool(workers) as pool:
            t0 = time.monotonic()
            counts = pool.map(_mp_shard, range(SHARDS))
            dt = time.monotonic() - t0
    finally:
        _MP_STACKED = None
    gbps = TOTAL_BYTES / dt / 1e9
    log(f"cpu multiprocess ({workers} workers / {cores} cores): "
        f"{dt * 1e3:.0f} ms (out={sum(counts)}) => {gbps:.3f} GB/s")
    return gbps, cores, workers


def bench_python(stacked):
    """Reference-style interpreter heap-merge, extrapolated from a sample."""
    from rocksplicator_tpu.storage.compaction import CpuCompactionBackend
    from rocksplicator_tpu.storage.merge import UInt64AddOperator

    sample = max(1, ENTRIES // 32)
    kb = (
        np.ascontiguousarray(stacked["key_words_be"][0][:sample].astype(">u4"))
        .view(np.uint8).reshape(sample, 24)
    )
    seqs = (stacked["seq_hi"][0][:sample].astype(np.uint64) << np.uint64(32)) | \
        stacked["seq_lo"][0][:sample].astype(np.uint64)
    vb = (
        np.ascontiguousarray(stacked["val_words"][0][:sample].astype("<u4"))
        .view(np.uint8).reshape(sample, VAL_BYTES)
    )
    entries = []
    for i in range(sample):
        entries.append((
            kb[i, :KEY_BYTES].tobytes(), int(seqs[i]),
            int(stacked["vtype"][0][i]),
            vb[i].tobytes() if stacked["vtype"][0][i] != 2 else b"",
        ))
    entries.sort(key=lambda e: (e[0], -e[1]))
    t0 = time.monotonic()
    list(CpuCompactionBackend().merge_runs(
        [entries], UInt64AddOperator(), True
    ))
    dt = time.monotonic() - t0
    gbps = sample * ENTRY_BYTES / dt / 1e9
    log(f"cpu python (heapq, {sample} sample): {dt * 1e3:.0f} ms "
        f"=> {gbps:.3f} GB/s")
    return gbps


def measure_write_stall_p99():
    """BASELINE target: write-stall p99 < 10 ms under a compaction storm.
    Runs a concurrent-writer storm against the real engine (tiny
    memtables + aggressive L0 trigger + depth-1 imm queue keep the
    background flusher saturated) and reads the storage.write_stall_ms
    histogram. Returns (p99_ms, samples) with samples > 0 — the storm
    escalates until writers demonstrably stalled, so the p99 reflects
    the real stall path, not a workload that never entered it."""
    import shutil
    import tempfile
    import threading

    from rocksplicator_tpu.storage.engine import DB, DBOptions
    from rocksplicator_tpu.utils.stats import Stats

    # background_compaction=True is load-bearing: without it writes take
    # the inline-flush path and the stall loop that records
    # storage.write_stall_ms can never run — rounds 1-3 reported a
    # vacuous "p99 = 0.00 ms, samples=0". The storm escalates pressure
    # until writers actually stall (imm queue full), so the reported p99
    # is a measurement, not an artifact of never entering the code path.
    for memtable_kb, n_writes, vlen in ((64, 8000, 512), (16, 8000, 2048)):
        Stats.reset_for_test()
        d = tempfile.mkdtemp(prefix="rstpu-bench-stall-")
        try:
            opts = DBOptions(
                memtable_bytes=memtable_kb << 10,
                level0_compaction_trigger=2,
                background_compaction=True,
            )
            db = DB(os.path.join(d, "db"), opts)
            val = b"v" * vlen
            # writer parallelism scaled to the host: on a 1-core box four
            # spinning writers measure GIL round-robin latency (~5ms
            # slices), not the engine's stall path
            n_writers = max(2, min(4, len(os.sched_getaffinity(0))))

            def writer(tid: int) -> None:
                for i in range(n_writes):
                    db.put(f"t{tid}k{i % 2048:08d}".encode(), val)

            threads = [threading.Thread(target=writer, args=(t,))
                       for t in range(n_writers)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            db.close()
            stats = Stats.get()
            p99 = stats.metric_percentile("storage.write_stall_ms", 99)
            n = stats.metric_count("storage.write_stall_ms")
            log(f"write-stall p99 under storm (memtable={memtable_kb}K "
                f"val={vlen}B): {p99:.2f} ms (samples={n})")
            if n > 0:
                return round(p99, 3), n
            log("storm produced zero stall samples — escalating pressure")
        finally:
            shutil.rmtree(d, ignore_errors=True)
    return None, 0


def _acquire_worker(start: float):
    """Bring up a ready TPU worker, retrying once on failure, degrading
    to the CPU platform as the last resort. Returns (worker, device_ok,
    backend_name). Round-3 postmortem: the 120s init default expired
    every round while the chip was in fact reachable (PERF.md measured
    it interactively) — init now gets the bulk of the time budget, a
    second attempt, and overlaps all the host-side phases that already
    ran before this is called."""
    init_budget = float(os.environ.get("BENCH_INIT_TIMEOUT", "0")) or max(
        600.0, TIME_BUDGET - (time.monotonic() - start))
    worker = _acquire_worker.pending or _TpuWorker()
    _acquire_worker.pending = None
    for attempt in (1, 2):
        t0 = time.monotonic()
        msg = worker.wait_ready(init_budget)
        if msg and msg.get("ok"):
            log(f"accelerator ready in {msg.get('init_sec', '?')}s "
                f"(attempt {attempt}, backend={msg.get('backend')})")
            return worker, True, msg.get("backend", "unknown")
        init_budget = float(
            os.environ.get("BENCH_INIT_RETRY_TIMEOUT", "240"))
        if msg is None:
            # Hung init: keep waiting on the SAME worker for the retry
            # window — a pool-side claim is queued behind other tenants,
            # and spawning a second claimant only adds contention (it
            # cannot overtake the first). Abandon only after the final
            # attempt (never kill — tunnel grant).
            log(f"accelerator init still pending after "
                f"{time.monotonic() - t0:.0f}s (attempt {attempt})")
            if attempt == 2:
                # keep the handle: if the tunnel comes up LATE (after the
                # degraded phases ran), the salvage pass at the end of
                # main() can still take one real-TPU measurement from it
                _acquire_worker.abandoned = worker
                worker.abandon()
        else:
            log(f"accelerator init failed (attempt {attempt}): "
                f"{msg.get('err')}")
            if attempt == 1:
                worker = _TpuWorker()  # died with an error: fresh claim
    # Wedged/absent accelerator: force the CPU platform so the run still
    # completes — and LABEL the result as degraded. The env propagates to
    # the fresh spawned worker, which calls _honor_platform_env (env
    # alone is not enough: sitecustomize re-registers the tunnel in every
    # fresh interpreter).
    log("falling back to CPU platform (degraded run)")
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    worker = _TpuWorker()
    msg = worker.wait_ready(120.0)
    if msg and msg.get("ok"):
        return worker, False, msg.get("backend", "cpu")
    worker.abandon()
    return None, False, "cpu"


_acquire_worker.pending = None
_acquire_worker.abandoned = None


# Best-so-far result shared with the SIGTERM handler: the batch-size
# climb can hit a minutes-long pool-side compile, and the driver's
# timeout must still receive a complete JSON line for the work that DID
# finish. Emission happens exactly once.
_RESULT = {"emitted": False, "data": None}


def _emit_result() -> None:
    if _RESULT["data"] is not None and not _RESULT["emitted"]:
        _RESULT["emitted"] = True
        print(json.dumps(_RESULT["data"]), flush=True)


def _finish() -> None:
    """Emit and exit, reaping abandoned workers first. With an orphan
    still alive, a normal interpreter exit blocks forever: the orphan
    holds the resource tracker's pipe open, and the parent's shutdown
    waitpid()s on the tracker (observed: bench hung after printing its
    JSON). Round-4's answer was a hard os._exit — which leaked the
    orphans' queue semaphores into the driver tail (resource_tracker
    warnings). Now: TERM each abandoned worker (allowed by the tunnel
    discipline — only SIGKILL wedges a grant), join briefly, and take
    the clean-exit path when they die; the hard exit remains only as
    the last resort for a worker that ignores TERM."""
    _emit_result()
    still_alive = False
    for proc, _cq, _rq in _TpuWorker._abandoned:
        try:
            if proc.is_alive():
                proc.terminate()
        except Exception as e:
            log(f"TERM of abandoned worker failed: {e!r}")
    for proc, cmd_q, res_q in _TpuWorker._abandoned:
        try:
            proc.join(5.0)
            if proc.is_alive():
                still_alive = True
            else:
                # release the queues' semaphores while the resource
                # tracker is still in a position to reap them
                for q in (cmd_q, res_q):
                    try:
                        q.close()
                        q.join_thread()
                    except Exception:
                        pass
        except Exception as e:
            log(f"join of abandoned worker failed: {e!r}")
            still_alive = True
    if still_alive:
        log("abandoned worker ignored TERM — hard exit (resource "
            "tracker would block a clean shutdown)")
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(0)


def _install_term_handler() -> None:
    import atexit
    import signal

    def on_term(signum, frame):
        log("SIGTERM: emitting best-so-far result")
        _emit_result()
        # reap still-registered (healthy) workers so their stderr pipe
        # closes too — SIGTERM, never SIGKILL (tunnel grant); abandoned
        # workers were deregistered, so TERM them explicitly as well
        # (no join — the exit below cannot wait on a wedged child)
        for child in list(_registered_children()):
            try:
                child.terminate()
            except Exception:
                pass
        for proc, _cq, _rq in _TpuWorker._abandoned:
            try:
                if proc.is_alive():
                    proc.terminate()
            except Exception:
                pass
        os._exit(0)

    signal.signal(signal.SIGTERM, on_term)
    # unhandled exceptions / normal exits also emit whatever is recorded
    atexit.register(_emit_result)


def main():
    log(f"bench config: shards={SHARDS} entries/shard={ENTRIES} "
        f"iters={ITERS} climb={CLIMB_SHARDS} budget={TIME_BUDGET}s")
    _install_term_handler()
    start = time.monotonic()
    # NOTE (round-5 fix): the accelerator worker is spawned AFTER the
    # timed host phases, not before. Rounds 1-4 overlapped jax init with
    # the host phases to hide the slow pool-side init — but on a 1-core
    # host the worker's XLA compile ran concurrently with the write-stall
    # storm and CPU baselines, polluting exactly the numbers the driver
    # records (r4: stall p99 17.4 ms under bench-inflicted contention vs
    # 4.0 ms clean). Init still gets its full 600 s floor
    # (_acquire_worker); the serialization costs ~1-2 min of wall clock.
    stacked = build_inputs()
    # CPU parallel baseline first: it forks, which must happen before
    # jax initializes a multithreaded runtime in THIS process (it never
    # does — see _TpuWorker — but keep the safe order anyway).
    try:
        mp_gbps, cores, workers = bench_numpy_multiproc(stacked)
    except Exception as e:  # a failed fork must not kill the JSON output
        log(f"cpu multiprocess baseline failed: {e!r}")
        mp_gbps, cores, workers = None, len(os.sched_getaffinity(0)), 1
    # Pessimistic until acquisition resolves: a SIGTERM mid-acquire must
    # emit the placeholder as degraded, not as a healthy run with no
    # accelerator number.
    device_ok = False
    platform = {"name": "unknown"}
    # fields that survive record() rebuilds (shootout results, chosen
    # sort backend)
    extras = {"sort_backend": _env_sort_backend()}

    def record(tpu_gbps, tpu_shards, tpu_xfer_gbps, accelerator=None):
        """Fold the current best TPU numbers + all host numbers into the
        emit-on-exit result. ``accelerator`` overrides the closure's
        ``device_ok`` (the late-salvage path records a real-chip number
        before flipping the flag).

        On a host with no accelerator the framework's production
        compaction path is the numpy fallback backend
        (TpuCompactionBackend falls back to NumpyCompactionBackend —
        tpu/backend.py), NOT the jax kernel emulated on CPU — so a
        degraded run's headline is the best measured FRAMEWORK number on
        this host, with value_source naming which path it came from.
        The degraded_no_accelerator flag still marks the run."""
        if accelerator is not None:
            on_accel = accelerator
        else:
            # device_ok means acquisition succeeded — which includes an
            # explicitly-requested JAX_PLATFORMS=cpu run; the label must
            # follow the backend the phases actually ran on
            on_accel = device_ok and platform["name"] not in (
                "cpu", "unknown")
        value, source = tpu_gbps, (
            "tpu_kernel" if on_accel else "jax_kernel_cpu_emulation")
        if not on_accel:
            cpu_name = _cpu_backend_name()
            for gbps, name in ((single_gbps, f"{cpu_name}_single_core"),
                               (py_gbps, "heap_merge_backend_single_core"),
                               (mp_gbps, f"{cpu_name}_multiproc")):
                if gbps and gbps > value:
                    value, source = gbps, name
        _RESULT["data"] = {
            "metric": "shard_batched_compaction_throughput",
            "value": round(value, 3),
            "unit": "GB/s",
            "value_source": source,
            # TPU-named field carries ONLY real-chip numbers (VERDICT
            # item 6a): on a CPU/emulation run it is null and the raw
            # jax-on-CPU number moves to an explicitly-emulated field,
            # so no JSON reader can mistake emulation for silicon.
            "tpu_kernel_gbps": round(tpu_gbps, 3) if on_accel else None,
            "tpu_kernel_emulated_gbps": (
                None if on_accel else round(tpu_gbps, 3)),
            "vs_baseline": round(value / cpu32_gbps, 3)
            if cpu32_gbps else 0.0,
            # machine consumers must tell a degraded run apart
            "platform": platform["name"],
            "degraded_no_accelerator": not device_ok,
            "tpu_shards": tpu_shards,
            "entries_per_shard": ENTRIES,
            "transfer_inclusive_gbps": round(tpu_xfer_gbps, 3)
            if tpu_xfer_gbps else None,
            "cpu_single_core_gbps": round(single_best, 3),
            "cpu_multiproc_gbps": round(mp_gbps, 3) if mp_gbps else None,
            "cpu_cores_available": cores,
            "cpu_32core_baseline_gbps": round(cpu32_gbps, 3),
            "cpu_32core_baseline_kind": cpu32_kind,
            "vs_single_core": round(value / single_best, 2)
            if single_best else 0.0,
            "write_stall_p99_ms": stall_p99,
            # 0 samples: no writer ever stalled during the storm — the
            # target holds trivially; consumers can see the distinction
            "write_stall_samples": stall_samples,
        }
        _RESULT["data"].update(extras)

    # Host-side numbers FIRST: they are cheap and every later phase
    # (including a hung first compile killed by the driver's timeout)
    # must be able to emit a complete JSON line around them.
    single_gbps = bench_numpy_single(stacked)
    py_gbps = bench_python(stacked)
    single_best = max(single_gbps, py_gbps)
    if workers >= BASELINE_CORES and mp_gbps:
        cpu32_gbps = mp_gbps
        cpu32_kind = f"measured_{workers}core"
    else:
        # perfect-scaling extrapolation — flattering to the CPU, so the
        # reported ratio is a lower bound on the real one
        cpu32_gbps = single_best * BASELINE_CORES
        cpu32_kind = "extrapolated_32x_single_core"
        if mp_gbps and workers > 1:
            # sanity: never extrapolate below what was actually measured
            cpu32_gbps = max(cpu32_gbps, mp_gbps)
    log(f"cpu 32-core baseline ({cpu32_kind}): {cpu32_gbps:.3f} GB/s")
    try:
        stall_p99, stall_samples = measure_write_stall_p99()
    except Exception as e:  # never let the stall probe kill the bench
        log(f"write-stall probe failed: {e!r}")
        stall_p99, stall_samples = None, None
    # placeholder so a TERM/crash during the first (riskiest) TPU compile
    # still emits a complete, clearly-incomplete-TPU result
    record(0.0, 0, None)
    _RESULT["data"]["tpu_phase_incomplete"] = True

    # All host phases done (and their timings clean) — only now spawn
    # and claim the accelerator worker.
    _acquire_worker.pending = _TpuWorker()
    os.environ.pop("BENCH_WORKER_INIT_DELAY", None)  # first worker only
    worker, device_ok, backend = _acquire_worker(start)
    platform["name"] = backend
    record(0.0, 0, None)
    _RESULT["data"]["tpu_phase_incomplete"] = True
    if worker is None:
        log("no usable backend at all — emitting host-only result")
        _salvage_late_accelerator(record, lambda: 60.0)
        _finish()
        return

    def budget_left():
        return max(60.0, TIME_BUDGET - (time.monotonic() - start))

    def phase(name, shards, timeout, kernel_gbps=0.0, sort_backend=None):
        """Run one phase on the persistent worker; a TIMEOUT abandons the
        worker and disables all further TPU phases (commands would just
        queue behind the wedged one)."""
        if worker.proc is None:
            return None
        res = worker.run_phase(name, shards, timeout, kernel_gbps,
                               sort_backend)
        if res is None:
            log(f"tpu phase {name}@{shards} timed out after {timeout:.0f}s")
            worker.abandon()
            worker.proc = None
        return res

    # first climb step: the guaranteed real-TPU number
    first = CLIMB_SHARDS[0] if CLIMB_SHARDS else SHARDS
    res = phase("kernel", first, budget_left() + 240)
    if not (res and res.get("ok")):
        log(f"tpu kernel bench at {first} shards failed: "
            f"{(res or {}).get('err', 'timeout')}")
        if not device_ok:
            _salvage_late_accelerator(record, budget_left)
        if worker.proc is not None:
            worker.quit()  # a hard exit would orphan a healthy worker
        _finish()  # the placeholder, marked incomplete
        return
    tpu_gbps, tpu_shards = res["gbps"], first
    platform["name"] = res["backend"]
    record(tpu_gbps, tpu_shards, None)

    # transfer-inclusive phase (8 shards, tunnel-bound)
    tpu_xfer_gbps = None
    res = phase("transfer", first, budget_left(), kernel_gbps=tpu_gbps)
    if res and res.get("ok"):
        tpu_xfer_gbps = res["gbps"]
    else:
        log(f"transfer-inclusive phase failed: "
            f"{(res or {}).get('err', 'timeout')}")
    record(tpu_gbps, tpu_shards, tpu_xfer_gbps)

    # Backend shootout — ON A REAL ACCELERATOR ONLY (interpret-mode
    # pallas on the CPU fallback takes minutes per trace): time the two
    # Pallas kernels at the same size, so the moment the pool grants a
    # chip the bench itself produces the lax/pallas/pallas_fused
    # comparison (the round-4 pending measurement) and the climb runs
    # the winner. A failed backend (e.g. VMEM overflow at this capacity)
    # is recorded as null and the shootout moves on; it runs AFTER the
    # transfer phase so a wedged pallas compile can only cost the climb.
    if (device_ok and platform["name"] != "cpu") or os.environ.get(
            "BENCH_FORCE_SHOOTOUT"):  # test seam: exercise on CPU
        shoot = {extras["sort_backend"]: round(tpu_gbps, 3)}
        best_b, best_g = extras["sort_backend"], tpu_gbps
        for b in ("lax", "pallas", "pallas_fused"):
            if b in shoot:
                continue
            if budget_left() <= 60 or worker.proc is None:
                break
            r2 = phase("kernel", first, budget_left(), sort_backend=b)
            if r2 and r2.get("ok"):
                shoot[b] = round(r2["gbps"], 3)
                log(f"shootout {b}: {r2['gbps']:.3f} GB/s")
                if r2["gbps"] > best_g:
                    best_b, best_g = b, r2["gbps"]
            else:
                shoot[b] = None
                log(f"shootout backend {b} failed: "
                    f"{(r2 or {}).get('err', 'timeout')}")
        extras["backend_shootout"] = shoot
        extras["sort_backend"] = best_b
        if best_g > tpu_gbps:
            tpu_gbps = best_g
            # the transfer number was measured with the env backend; a
            # cross-backend kernel/transfer pairing is meaningless (same
            # rule as the late-salvage path), so drop it with the win
            tpu_xfer_gbps = None
        # merge the shootout into the emitted JSON even when the
        # starting backend won and nothing improved
        record(tpu_gbps, tpu_shards, tpu_xfer_gbps)

    # climb: larger batches amortize the per-dispatch floor. Compiles are
    # cheap now (warm worker + persistent cache) but still bounded by the
    # budget; SIGTERM mid-step still emits best-so-far. A degraded
    # (CPU-fallback) run skips the climb: its number is only ever
    # consumed as a labeled-degraded value.
    for shards in (CLIMB_SHARDS[1:] if device_ok else ()):
        elapsed = time.monotonic() - start
        if elapsed > TIME_BUDGET:
            log(f"climb stopped at {tpu_shards} shards "
                f"({elapsed:.0f}s > {TIME_BUDGET:.0f}s budget)")
            break
        res = phase("kernel", shards, budget_left(),
                    sort_backend=extras["sort_backend"])
        if not (res and res.get("ok")):
            log(f"climb step {shards} shards failed: "
                f"{(res or {}).get('err', 'timeout')}")
            break
        if res["gbps"] > tpu_gbps:
            tpu_gbps, tpu_shards = res["gbps"], shards
            record(tpu_gbps, tpu_shards, tpu_xfer_gbps)

    if not device_ok:
        _salvage_late_accelerator(record, budget_left)
    if worker.proc is not None:
        worker.quit()
    _finish()


def _salvage_late_accelerator(record, budget_left):
    """Degraded runs only: the worker abandoned during acquisition keeps
    initializing in the background. If the pool granted a chip while the
    CPU-fallback phases ran, take ONE real-TPU kernel measurement from
    it now — rounds 1-3 produced zero driver-captured TPU numbers, so a
    late grant is worth the extra minutes."""
    late = _acquire_worker.abandoned
    if late is None:
        return
    # whatever happens below, this worker is either recovered for one
    # measurement or reaped — no path leaves it orphaned for the rest of
    # the run (VERDICT item 6b: "recover or reap before exit")
    _acquire_worker.abandoned = None
    try:
        # short grace window (a just-granted chip may be mid-handshake;
        # a non-blocking poll can also miss a still-in-pipe message)
        msg = late.res_q.get(timeout=float(
            os.environ.get("BENCH_SALVAGE_WAIT", "20")))
    except queue_mod.Empty:
        log("late-salvage: abandoned worker still not ready — reaping")
        late.reap()
        return
    except Exception as e:
        log(f"late-salvage: {e!r}")
        late.reap()
        return
    if not (msg and msg.get("ok")):
        log(f"late-salvage: abandoned worker failed: {msg}")
        late.reap()
        return
    backend = msg.get("backend", "unknown")
    if backend == "cpu":
        # no chip was granted after all — don't burn minutes measuring a
        # CPU number only to discard it
        log("late-salvage: worker came up on backend=cpu — skipping")
        late.quit()
        late.reap()
        return
    log(f"late-salvage: accelerator came up AFTER fallback "
        f"(backend={backend}, init={msg.get('init_sec')}s) — measuring")
    first = CLIMB_SHARDS[0] if CLIMB_SHARDS else SHARDS
    res = late.run_phase("kernel", first, budget_left() + 240)
    if res and res.get("ok") and res.get("backend") != "cpu":
        # a real accelerator number replaces the degraded CPU one. The
        # transfer-inclusive number (if any) came from the CPU fallback
        # worker — a cross-backend ratio is meaningless, so drop it.
        record(res["gbps"], first, None, accelerator=True)
        _RESULT["data"]["platform"] = res["backend"]
        _RESULT["data"]["degraded_no_accelerator"] = False
        _RESULT["data"]["late_salvage"] = True
        _RESULT["data"].pop("tpu_phase_incomplete", None)
        log(f"late-salvage: kernel {res['gbps']:.3f} GB/s recorded")
        late.quit()
        late.reap()
    elif res and res.get("ok"):
        # phase ran but on the CPU backend: not an accelerator number —
        # the degraded result stands
        log(f"late-salvage: worker came up on backend="
            f"{res.get('backend')} — not recording")
        late.quit()
        late.reap()
    else:
        log(f"late-salvage measurement failed: "
            f"{(res or {}).get('err', 'timeout')}")
        # already in _abandoned from acquisition (abandon() here again
        # would double-register it); TERM+join it now instead of leaving
        # an orphan until interpreter exit
        late.reap()


if __name__ == "__main__":
    if "--macro_bench" in sys.argv:
        # serving-scale macro-bench mode (round 13): YCSB-style mixed
        # workload (zipfian keys, Poisson open-loop arrival) over a
        # 3-replica cluster via router read policies — no accelerator
        # worker, no kernel compiles. Other args pass through to
        # benchmarks/macro_bench.py.
        from benchmarks.macro_bench import main as macro_bench_main

        argv = [a for a in sys.argv[1:] if a != "--macro_bench"]
        sys.exit(macro_bench_main(argv))
    if "--flush_bench" in sys.argv:
        # engine microbench mode (round 9): flush / host-compaction /
        # block-cache A/B — no accelerator worker, no kernel compiles.
        # All other args pass through to benchmarks/flush_bench.py.
        from benchmarks.flush_bench import main as flush_bench_main

        argv = [a for a in sys.argv[1:] if a != "--flush_bench"]
        sys.exit(flush_bench_main(argv))
    if "--compaction_bench" in sys.argv:
        # compaction-scheduler A/B mode (round 16): mixed-load engine
        # slice of the macro-bench with the workload-adaptive scheduler
        # interleaved on/off. Args pass through to
        # benchmarks/compaction_bench.py.
        from benchmarks.compaction_bench import main as compaction_bench_main

        argv = [a for a in sys.argv[1:] if a != "--compaction_bench"]
        sys.exit(compaction_bench_main(argv))
    main()
