#!/usr/bin/env python
"""Headline benchmark: shard-batched TPU compaction throughput vs CPU.

Models BASELINE config ladder steps 1-3 in miniature: S shards of counter
workload (PUT/MERGE/DELETE mix) run the fused merge-resolve + bloom
pipeline. The TPU number is the vmapped single-launch pipeline; the CPU
baseline is the best of (vectorized numpy lexsort+reduceat, pure-Python
heap-merge extrapolated) on the identical workload.

Prints exactly ONE JSON line on stdout:
    {"metric": ..., "value": N, "unit": "GB/s", "vs_baseline": N}
Diagnostics go to stderr.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np


def log(msg):
    print(msg, file=sys.stderr, flush=True)


SHARDS = int(os.environ.get("BENCH_SHARDS", "8"))
ENTRIES = int(os.environ.get("BENCH_ENTRIES", str(1 << 17)))
ITERS = int(os.environ.get("BENCH_ITERS", "10"))
KEY_BYTES = 16
VAL_BYTES = 8
# what a CPU compaction would read per entry in the SST encoding:
# u32 klen + key + u64 seq + u8 vtype + u32 vlen + value
ENTRY_BYTES = 4 + KEY_BYTES + 8 + 1 + 4 + VAL_BYTES


def build_inputs():
    from rocksplicator_tpu.models.compaction_model import synth_counter_batch

    shards = []
    for s in range(SHARDS):
        shards.append(synth_counter_batch(
            ENTRIES, key_space=ENTRIES // 8, seed=1234 + s,
            key_bytes=KEY_BYTES,
        ))
    stacked = {
        k: np.stack([b[k] for b in shards]) for k in shards[0]
    }
    return stacked


def _probe_devices(q):
    """Watchdog child (module-level: spawn must pickle it)."""
    try:
        import jax

        jax.devices()
        q.put(True)
    except Exception:
        q.put(False)


def _start_device_watchdog():
    """Spawn the accelerator-init probe (overlaps with input building)."""
    import multiprocessing

    ctx = multiprocessing.get_context("spawn")
    q = ctx.Queue()
    p = ctx.Process(target=_probe_devices, args=(q,), daemon=True)
    p.start()
    return p, q


def _join_device_watchdog(p, q, timeout_sec: float = 120.0) -> bool:
    """True iff the accelerator initialized within the timeout. A wedged
    TPU tunnel must degrade the bench to CPU, never hang it."""
    p.join(timeout_sec)
    if p.is_alive():
        p.kill()
        p.join(5)
        return False
    try:
        return bool(q.get_nowait())
    except Exception:
        return False


def bench_tpu(stacked):
    import jax
    import jax.numpy as jnp

    from rocksplicator_tpu.models import CompactionModel

    model = CompactionModel(capacity=ENTRIES, uniform_klen=True, seq32=True)
    fwd = jax.jit(jax.vmap(model.forward))
    log(f"jax backend: {jax.default_backend()}, devices: {jax.devices()}")
    dev = {k: jnp.asarray(v) for k, v in stacked.items()}
    args = (
        dev["key_words_be"], dev["key_words_le"], dev["key_len"],
        dev["seq_hi"], dev["seq_lo"], dev["vtype"], dev["val_words"],
        dev["val_len"], dev["valid"],
    )
    t0 = time.monotonic()
    out = fwd(*args)
    jax.block_until_ready(out)
    log(f"tpu compile+first run: {time.monotonic() - t0:.1f}s, "
        f"counts={np.asarray(out['count'])[:4]}...")
    # steady state
    t0 = time.monotonic()
    for _ in range(ITERS):
        out = fwd(*args)
    jax.block_until_ready(out)
    dt = (time.monotonic() - t0) / ITERS
    total_bytes = SHARDS * ENTRIES * ENTRY_BYTES
    gbps = total_bytes / dt / 1e9
    log(f"tpu: {dt * 1e3:.1f} ms/iter over {total_bytes / 1e6:.0f} MB "
        f"=> {gbps:.2f} GB/s")

    # transfer-inclusive variant (fresh H2D each iteration)
    t0 = time.monotonic()
    for _ in range(max(1, ITERS // 3)):
        dev2 = {k: jnp.asarray(v) for k, v in stacked.items()}
        out = fwd(
            dev2["key_words_be"], dev2["key_words_le"], dev2["key_len"],
            dev2["seq_hi"], dev2["seq_lo"], dev2["vtype"],
            dev2["val_words"], dev2["val_len"], dev2["valid"],
        )
        jax.block_until_ready(out)
    dt_x = (time.monotonic() - t0) / max(1, ITERS // 3)
    log(f"tpu transfer-inclusive: {dt_x * 1e3:.1f} ms/iter "
        f"=> {total_bytes / dt_x / 1e9:.2f} GB/s")
    return gbps


def bench_numpy(stacked):
    from rocksplicator_tpu.ops.kv_format import KVBatch
    from rocksplicator_tpu.tpu.backend import numpy_merge_resolve
    from rocksplicator_tpu.storage.bloom import BloomFilter, num_words_for

    def one_pass():
        total = 0
        for s in range(SHARDS):
            batch = KVBatch(
                key_words_be=stacked["key_words_be"][s],
                key_words_le=stacked["key_words_le"][s],
                key_len=stacked["key_len"][s],
                seq_hi=stacked["seq_hi"][s],
                seq_lo=stacked["seq_lo"][s],
                vtype=stacked["vtype"][s],
                val_words=stacked["val_words"][s],
                val_len=stacked["val_len"][s],
                valid=stacked["valid"][s],
                val_bytes=VAL_BYTES,
            )
            arrays, count = numpy_merge_resolve(
                batch, uint64_add=True, drop_tombstones=True
            )
            # bloom build is part of the compaction job on CPU too
            bf = BloomFilter(num_words_for(count or 1, 10))
            kw = arrays[0]
            kl = arrays[1]
            kb = (
                np.ascontiguousarray(kw.astype(">u4"))
                .view(np.uint8).reshape(len(kw), 24)
            )
            for i in range(count):
                bf.add(kb[i, : kl[i]].tobytes())
            total += count
        return total

    t0 = time.monotonic()
    total = one_pass()
    dt = time.monotonic() - t0
    total_bytes = SHARDS * ENTRIES * ENTRY_BYTES
    gbps = total_bytes / dt / 1e9
    log(f"numpy cpu: {dt * 1e3:.0f} ms/pass (out={total}) => {gbps:.3f} GB/s")
    return gbps


def bench_python(stacked):
    """Reference-style interpreter heap-merge, extrapolated from a sample."""
    from rocksplicator_tpu.ops.kv_format import KVBatch, unpack_entries
    from rocksplicator_tpu.storage.compaction import CpuCompactionBackend
    from rocksplicator_tpu.storage.merge import UInt64AddOperator

    sample = max(1, ENTRIES // 32)
    kb = (
        np.ascontiguousarray(stacked["key_words_be"][0][:sample].astype(">u4"))
        .view(np.uint8).reshape(sample, 24)
    )
    seqs = (stacked["seq_hi"][0][:sample].astype(np.uint64) << np.uint64(32)) | \
        stacked["seq_lo"][0][:sample].astype(np.uint64)
    vb = (
        np.ascontiguousarray(stacked["val_words"][0][:sample].astype("<u4"))
        .view(np.uint8).reshape(sample, VAL_BYTES)
    )
    entries = []
    for i in range(sample):
        entries.append((
            kb[i, :KEY_BYTES].tobytes(), int(seqs[i]),
            int(stacked["vtype"][0][i]),
            vb[i].tobytes() if stacked["vtype"][0][i] != 2 else b"",
        ))
    entries.sort(key=lambda e: (e[0], -e[1]))
    t0 = time.monotonic()
    out = list(CpuCompactionBackend().merge_runs(
        [entries], UInt64AddOperator(), True
    ))
    dt = time.monotonic() - t0
    gbps = sample * ENTRY_BYTES / dt / 1e9
    log(f"python cpu (heapq, {sample} sample): {dt * 1e3:.0f} ms "
        f"=> {gbps:.3f} GB/s")
    return gbps


def main():
    log(f"bench config: shards={SHARDS} entries/shard={ENTRIES} iters={ITERS}")
    wd = _start_device_watchdog()  # overlaps with input construction
    stacked = build_inputs()
    device_ok = _join_device_watchdog(
        *wd, float(os.environ.get("BENCH_INIT_TIMEOUT", "120"))
    )
    if not device_ok:
        # Wedged/absent accelerator: force the CPU platform so the run
        # still completes — and LABEL the result as degraded.
        log("accelerator init timed out — falling back to CPU platform")
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        import __graft_entry__ as graft

        graft._honor_platform_env()
    import jax

    tpu_gbps = bench_tpu(stacked)
    numpy_gbps = bench_numpy(stacked)
    py_gbps = bench_python(stacked)
    baseline = max(numpy_gbps, py_gbps)
    result = {
        "metric": "shard_batched_compaction_throughput",
        "value": round(tpu_gbps, 3),
        "unit": "GB/s",
        "vs_baseline": round(tpu_gbps / baseline, 2) if baseline > 0 else 0.0,
        # machine consumers must be able to tell a degraded run apart
        "platform": jax.default_backend(),
        "degraded_no_accelerator": not device_ok,
    }
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
